"""Fault-injection bench: inject each documented failure class and assert
the documented recovery (runtime/resilience). CPU-only by design — the
recovery *logic* is backend-independent, and proving it must never burn a
chip window. One JSON row per scenario; exit 1 if any recovery contract
fails.

| fault class            | injection                                   | documented recovery                          |
|------------------------|---------------------------------------------|----------------------------------------------|
| torn save (crash)      | SIGKILL between staging and atomic rename   | partial tag invisible; previous tag loads    |
| truncated checkpoint   | truncate largest manifest-listed file       | verified fallback to newest intact tag       |
| bit-flipped checkpoint | flip one bit in array data                  | verified fallback to newest intact tag       |
| persistent NaN grads   | inf loss boost through real overflow path   | abort after K consecutive skips (loud)       |
| SIGKILL mid-run        | DS_FAULT_SPEC step=sigkill@N under agent    | restart + bit-exact resumed loss curve       |
| transient HTTP 500     | compile-helper-500-shaped flaky call        | retried with backoff; attempts in evidence   |
| SIGTERM mid-serve      | real SIGTERM to a serving subprocess        | in-flight drained to full budget, queue      |
|                        |                                             | refused, exit 143 (graft-serve drain)        |
| scale-up (4 -> 8)      | SIGKILL at step k on 4 virtual devices,     | resume_elastic reshards the verified         |
|                        | agent relaunches on 8 (graft-elastic)       | checkpoint; curve in envelope; W->W'->W      |
|                        |                                             | leaf digests bit-identical                   |
| scale-down (4 -> 2)    | same, relaunched on 2 virtual devices       | same contract in the gather direction        |
| SIGTERM fleet replica  | sigterm one of two router-driven replicas   | in-flight KV migrates to the peer through a  |
|                        | mid-flight (graft-fleet)                    | digest-verified bundle; zero dropped; greedy |
|                        |                                             | parity with an uninterrupted run             |
| SIGKILL fleet replica  | hard-kill a replica, no drain, no bundle    | router re-admits orphaned requests on the    |
|                        |                                             | peer at-most-once; zero dropped; bounded     |
|                        |                                             | TTFT spike                                   |
| SIGTERM mid RLHF loop  | real SIGTERM after >=1 learner step of the  | in-flight rollouts drained + banked (zero    |
|                        | in-flight rollout loop (graft-rlhf)         | dropped), learner checkpoints at a boundary, |
|                        |                                             | resumed run stitches the loss curve within   |
|                        |                                             | RLHF_STITCH_LOSS_RTOL of uninterrupted       |

Run: python tools/fault_bench.py            (scenario subset: FAULT_SCENARIOS=...)
Tests import the scenario functions directly (tests/unit/resilience/).
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PY = sys.executable

# -- shared tiny-engine builder (in-process scenarios) -----------------------

def _tiny_engine(ds_extra=None, loss_fn=None):
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    ds = {"train_batch_size": 8,
          "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
          "steps_per_print": 1}
    ds.update(ds_extra or {})
    cfg = get_gpt2_config("test")
    engine, _, _, _ = deepspeed_tpu.initialize(model=GPT2LMHeadModel(cfg),
                                               config=ds, loss_fn=loss_fn)
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 16)).astype(np.int32)}
    return engine, batch


def _row(fault, expected, observed, ok, **extra):
    return dict({"fault": fault, "expected": expected, "observed": observed,
                 "ok": bool(ok)}, **extra)


# -- corruption scenarios (in-process) ---------------------------------------

def scenario_corrupt_checkpoint(workdir, mode="truncate"):
    """Damage the newest tag; load must fall back to the previous intact one
    — not crash, not silently load garbage."""
    from deepspeed_tpu.runtime.resilience.faults import corrupt_checkpoint
    ckpt = os.path.join(workdir, f"ckpt_{mode}")
    engine, batch = _tiny_engine()
    engine.train_batch(batch)
    engine.save_checkpoint(ckpt, tag="t1")
    engine.train_batch(batch)
    engine.save_checkpoint(ckpt, tag="t2")
    corrupt_checkpoint(ckpt, "t2", mode=mode)
    fresh, _ = _tiny_engine()
    fresh.initialize_state(batch)
    fresh.load_checkpoint(ckpt)
    loaded = getattr(fresh, "_loaded_checkpoint_tag", None)
    return _row(f"{mode}_checkpoint", "fallback to t1", f"loaded {loaded}",
                loaded == "t1" and fresh.global_steps == 1)


def scenario_all_corrupt(workdir):
    """Every tag damaged: the failure must be LOUD (CheckpointCorruptError),
    never a silent load of garbage params."""
    from deepspeed_tpu.runtime.resilience.faults import corrupt_checkpoint
    from deepspeed_tpu.runtime.resilience.manifest import CheckpointCorruptError
    ckpt = os.path.join(workdir, "ckpt_all_corrupt")
    engine, batch = _tiny_engine()
    engine.train_batch(batch)
    engine.save_checkpoint(ckpt, tag="only")
    corrupt_checkpoint(ckpt, "only", mode="bitflip")
    fresh, _ = _tiny_engine()
    fresh.initialize_state(batch)
    try:
        fresh.load_checkpoint(ckpt)
        observed = "loaded silently"
    except CheckpointCorruptError as e:
        observed = f"raised CheckpointCorruptError: {str(e)[:80]}"
    return _row("all_tags_corrupt", "loud CheckpointCorruptError",
                observed, observed.startswith("raised"))


# -- poisoned numerics -------------------------------------------------------

def scenario_overflow_abort(workdir, abort_after=3):
    """Persistent non-finite gradients: K consecutive overflow-skips must
    abort the run (fail fast), through the REAL grad/overflow machinery."""
    from deepspeed_tpu.runtime.fp16.loss_scaler import OverflowAbort
    from deepspeed_tpu.runtime.resilience.faults import overflow_injected_loss, poison_batch
    engine, batch = _tiny_engine(
        ds_extra={"resilience": {"max_consecutive_overflows": abort_after}},
        loss_fn=overflow_injected_loss())
    engine.train_batch(batch)  # healthy step first: streak must start at the poison
    poisoned = poison_batch(batch)
    steps_survived = 0
    observed = f"no abort after {abort_after + 2} poisoned steps"
    try:
        for _ in range(abort_after + 2):
            engine.train_batch(poisoned)
            steps_survived += 1
    except OverflowAbort as e:
        observed = f"OverflowAbort after {steps_survived + 1} poisoned steps: {str(e)[:60]}"
    return _row("persistent_nan_grads", f"OverflowAbort after {abort_after} skips",
                observed, steps_survived + 1 == abort_after and "OverflowAbort" in observed,
                skipped_total=int(engine._skipped_steps))


# -- transient infrastructure ------------------------------------------------

def scenario_http500_retry(workdir, fails=2):
    """Transient compile-helper 500s: retried with backoff, each attempt in
    the evidence row (the exact message text the tunnel produces)."""
    from deepspeed_tpu.runtime.resilience.faults import FlakyCall
    from deepspeed_tpu.runtime.resilience.retry import COMPILE_HELPER_500, RetryPolicy
    flaky = FlakyCall(lambda: "banked", fails=fails)
    policy = RetryPolicy(max_attempts=fails + 1, base_delay=0.01, jitter=0.25,
                         seed=0, sleep=lambda s: None)
    result = policy.call(flaky)
    ev = policy.evidence()
    ok = (result == "banked" and flaky.calls == fails + 1
          and ev.get("retries") == fails
          and all(a["error_class"] == COMPILE_HELPER_500 for a in ev["retry_history"]))
    return _row("transient_http500", f"success after {fails} retries, history recorded",
                f"result={result!r} calls={flaky.calls}", ok, **ev)


# -- process-death scenarios (subprocess) ------------------------------------

_TORN_SAVE_CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", os.path.join({repo!r}, ".jax_cache"))
    import numpy as np, deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    cfg = get_gpt2_config("test")
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg),
        config={{"train_batch_size": 8,
                 "optimizer": {{"type": "Adam", "params": {{"lr": 1e-3}}}}}})
    batch = {{"input_ids": np.zeros((8, 16), np.int32)}}
    eng.train_batch(batch)
    eng.save_checkpoint({ckpt!r}, tag="good")
    eng.train_batch(batch)
    os.environ["DS_FAULT_SPEC"] = "ckpt_pre_rename=sigkill"   # die mid-publish
    eng.save_checkpoint({ckpt!r}, tag="torn")
    print("UNREACHABLE")
""")


def scenario_torn_save(workdir):
    """SIGKILL between checkpoint staging and the atomic rename: the torn
    tag must be INVISIBLE (staging dir only), 'latest' still names the
    previous tag, and a fresh engine loads it cleanly."""
    from envutil import cpu_subprocess_env
    ckpt = os.path.join(workdir, "ckpt_torn")
    p = subprocess.run([PY, "-c", _TORN_SAVE_CHILD.format(repo=REPO, ckpt=ckpt)],
                       env=cpu_subprocess_env(), capture_output=True, text=True,
                       timeout=420, cwd=REPO)
    killed = p.returncode == -9 and "UNREACHABLE" not in p.stdout
    entries = sorted(os.listdir(ckpt)) if os.path.isdir(ckpt) else []
    torn_invisible = "torn" not in entries and ".tmp.torn" in entries
    latest_ok = open(os.path.join(ckpt, "latest")).read().strip() == "good"
    # recovery leg: a fresh engine resumes from 'good' and its next save
    # sweeps the stale staging dir
    fresh, batch = _tiny_engine()
    fresh.initialize_state(batch)
    fresh.load_checkpoint(ckpt)
    resumed_ok = fresh._loaded_checkpoint_tag == "good" and fresh.global_steps == 1
    fresh.save_checkpoint(ckpt, tag="after")
    swept = ".tmp.torn" not in os.listdir(ckpt)
    return _row("torn_save_sigkill",
                "partial tag invisible; latest->good; resume ok; staging swept",
                f"killed={killed} entries={entries} resumed={fresh._loaded_checkpoint_tag} "
                f"swept={swept}",
                killed and torn_invisible and latest_ok and resumed_ok and swept)


_TRAIN_CHILD = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", os.path.join({repo!r}, ".jax_cache"))
    import numpy as np, jax.numpy as jnp, deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    restarted = os.environ.get("DS_ELASTIC_RESTART_COUNT", "0") != "0"
    if restarted:
        os.environ.pop("DS_FAULT_SPEC", None)   # fault fires on the first life only
    cfg = get_gpt2_config("test", n_layer=2)
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg),
        config={{"train_batch_size": 8,
                 "optimizer": {{"type": "Adam", "params": {{"lr": 1e-3}}}}}})
    eng.initialize_state({{"input_ids": np.zeros((8, 16), np.int32)}})
    eng.resume({ckpt!r})     # fresh start on the first life, verified resume after
    while eng.global_steps < {total}:
        step = eng.global_steps
        rng = np.random.RandomState(1000 + step)
        batch = {{"input_ids": rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)}}
        loss = float(jnp.asarray(eng.train_batch(batch)))
        with open({losses!r}, "a") as f:
            f.write(json.dumps({{"step": step, "loss": loss.hex()}}) + chr(10))
        eng.save_checkpoint({ckpt!r})
        from deepspeed_tpu.elasticity.elastic_agent import touch_heartbeat
        touch_heartbeat(payload={{"global_step": eng.global_steps,
                                  "last_span": "checkpoint"}})
    print("CHILD_DONE", eng.global_steps)
""")


def run_supervised(workdir, name, total, fault_env):
    """One supervised training run (DSElasticAgent around a CPU child that
    trains ``total`` steps with per-step deterministic data, checkpointing
    and resuming via engine.resume). Returns ``(rc, agent, {step: loss_hex})``
    — losses as exact float hex so comparisons are bit-level, not approx."""
    from envutil import cpu_subprocess_env
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent

    d = os.path.join(workdir, name)
    os.makedirs(d, exist_ok=True)
    losses = os.path.join(d, "losses.jsonl")
    child = _TRAIN_CHILD.format(repo=REPO, ckpt=os.path.join(d, "ckpt"),
                                losses=losses, total=total)
    env = cpu_subprocess_env()
    env.update(fault_env)
    agent = DSElasticAgent([PY, "-c", child], world_sizes=[1],
                           heartbeat_timeout=300.0, max_restarts=1, env=env)
    rc = agent.run(workdir=d)
    rows = [json.loads(l) for l in open(losses)] if os.path.exists(losses) else []
    return rc, agent, {r["step"]: r["loss"] for r in rows}


def scenario_sigkill_resume(workdir, kill_at=2, total=4):
    """SIGKILL at a step boundary under DSElasticAgent: the agent restarts
    the child, resume() restores the timeline, and the stitched loss curve
    is BIT-identical to an uninterrupted run (losses compared as exact
    float hex)."""
    rc, agent, losses = run_supervised(workdir, "faulted", total,
                                       {"DS_FAULT_SPEC": f"step=sigkill@{kill_at}"})
    ref_rc, _, ref_losses = run_supervised(workdir, "reference", total, {})
    bit_exact = (losses == ref_losses and len(ref_losses) == total)
    # how far each attempt got, from the heartbeat payload the agent
    # snapshots at attempt end (not just that the child was alive)
    progress = [h.get("last_heartbeat") for h in agent.history]
    return _row("sigkill_midrun_resume",
                f"agent restart + bit-exact {total}-step curve",
                f"rc={rc} restarts={agent.restart_count} steps={sorted(losses)} "
                f"bit_exact={bit_exact} progress={progress}",
                rc == 0 and ref_rc == 0 and agent.restart_count == 1 and bit_exact,
                attempt_progress=progress)


# -- elastic resharding scenarios (graft-elastic: subprocess, world change) --

#: documented loss-curve envelope for a world-size change: the stitched
#: post-reshard curve vs the uninterrupted fixed-world reference. Data and
#: RNG are step-deterministic and the restored leaves are digest-proven
#: bit-identical, so the only drift source is cross-world reduction order
#: (fp32 on CPU) — same envelope the cross-world elasticity test has
#: carried since PR 4 (tests/unit/elasticity/test_elastic_agent.py).
RESHARD_LOSS_RTOL = 2e-4

_ELASTIC_CHILD = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    world = int(os.environ["DS_ELASTIC_WORLD_SIZE"])
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(f for f in os.environ.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f)
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={{world}}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", os.path.join({repo!r}, ".jax_cache"))
    import numpy as np, jax.numpy as jnp, deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology

    if os.environ.get("DS_ELASTIC_RESTART_COUNT", "0") != "0":
        os.environ.pop("DS_FAULT_SPEC", None)   # fault fires on the first life only
    cfg = get_gpt2_config("test", n_layer=2)
    # stage 3 + persistence threshold 0: every param fsdp-sharded, so a
    # world change genuinely re-chunks the whole state
    eng, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), topology=MeshTopology(fsdp=world),
        config={{"train_batch_size": 8,
                 "optimizer": {{"type": "Adam", "params": {{"lr": 1e-3}}}},
                 "zero_optimization": {{"stage": 3,
                                        "stage3_param_persistence_threshold": 0}}}})
    eng.initialize_state({{"input_ids": np.zeros((8, 16), np.int32)}})
    report = eng.resume_elastic({ckpt!r})   # fresh / plain / reshard by topology
    with open({modes!r}, "a") as f:
        f.write(json.dumps({{"world": world, "mode": report.mode, "tag": report.tag,
                             "gather_bytes": report.gather_bytes}}) + chr(10))
    rt = os.environ.get("DS_ROUNDTRIP_TAG")
    if rt:   # round-trip probe: re-save the resumed state untouched, then exit
        eng.save_checkpoint({ckpt!r}, tag=rt, save_latest=False)
        print("ROUNDTRIP_SAVED", rt)
        sys.exit(0)
    while eng.global_steps < {total}:
        step = eng.global_steps
        rng = np.random.RandomState(1000 + step)
        batch = {{"input_ids": rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)}}
        loss = float(jnp.asarray(eng.train_batch(batch)))
        with open({losses!r}, "a") as f:
            f.write(json.dumps({{"step": step, "world": world, "loss": loss.hex()}}) + chr(10))
        eng.save_checkpoint({ckpt!r})
        from deepspeed_tpu.elasticity.elastic_agent import touch_heartbeat
        touch_heartbeat(payload={{"global_step": eng.global_steps,
                                  "last_span": "checkpoint"}})
    print("CHILD_DONE", eng.global_steps)
""")


def run_elastic(workdir, name, total, fault_env, world_sizes, roundtrip_tag=None):
    """One supervised ELASTIC run: DSElasticAgent around a CPU child that
    pins its own virtual-device count to ``DS_ELASTIC_WORLD_SIZE``, trains
    with per-step deterministic data, and comes up through
    ``resume_elastic``. Returns ``(rc, agent, {step: loss_hex}, modes)``
    where ``modes`` records each life's resume decision."""
    from envutil import cpu_subprocess_env
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent

    d = os.path.join(workdir, name)
    os.makedirs(d, exist_ok=True)
    ckpt = os.path.join(d, "ckpt")
    losses = os.path.join(d, "losses.jsonl")
    modes = os.path.join(d, "modes.jsonl")
    child = _ELASTIC_CHILD.format(repo=REPO, ckpt=ckpt, losses=losses,
                                  modes=modes, total=total)
    env = cpu_subprocess_env()
    env.pop("XLA_FLAGS", None)  # the child pins its own device count
    env.update(fault_env)
    if roundtrip_tag:
        env["DS_ROUNDTRIP_TAG"] = roundtrip_tag
    agent = DSElasticAgent([PY, "-c", child], world_sizes=list(world_sizes),
                           heartbeat_timeout=300.0, max_restarts=1, env=env,
                           checkpoint_dir=ckpt)
    rc = agent.run(workdir=d)
    rows = [json.loads(l) for l in open(losses)] if os.path.exists(losses) else []
    mode_rows = [json.loads(l) for l in open(modes)] if os.path.exists(modes) else []
    return rc, agent, {r["step"]: r["loss"] for r in rows}, mode_rows


_ELASTIC_REF = {}  # total -> {step: loss_hex} (shared fixed-world-4 reference)


def _elastic_reference(workdir, total):
    """Uninterrupted world-4 reference run (shared by scale_up/scale_down —
    one subprocess life per bench process)."""
    if total not in _ELASTIC_REF:
        rc, _, losses, modes = run_elastic(workdir, f"ref4_{total}", total, {}, [4])
        assert rc == 0 and modes[0]["mode"] == "fresh", (rc, modes)
        _ELASTIC_REF[total] = losses
    return _ELASTIC_REF[total]


def _manifest_digests(ckpt, tag):
    with open(os.path.join(ckpt, tag, "manifest.json")) as f:
        leaves = json.load(f)["leaves"]
    return {k: v["sha256"] for k, v in leaves.items()}


def scenario_scale(workdir, new_world, kill_at=2, total=3):
    """SIGKILL at step ``kill_at`` on 4 virtual devices; the elastic agent
    relaunches at ``new_world``; ``resume_elastic`` reshards the verified
    checkpoint onto the new mesh. Asserts: (a) the relaunched life reports
    mode=reshard with nonzero gather bytes and the agent's history row
    records the 4 -> ``new_world`` transition; (b) pre-kill steps are
    BIT-identical to the fixed-world reference and post-reshard steps stay
    inside :data:`RESHARD_LOSS_RTOL`; (c) a world-4 round-trip probe
    (W -> W' -> W) re-saves leaf digests bit-identical to the final W'
    checkpoint — the reshard moved every byte and invented none."""
    name = f"scale_{new_world}"
    rc, agent, losses, modes = run_elastic(
        workdir, name, total, {"DS_FAULT_SPEC": f"step=sigkill@{kill_at}"},
        [4, new_world])
    ref = _elastic_reference(workdir, total)
    ok = rc == 0 and agent.restart_count == 1 and agent.history[0]["rc"] == -9
    complete = sorted(losses) == list(range(total)) and len(modes) == 2
    if complete:
        ok = ok and modes[0]["mode"] == "fresh" and modes[1]["mode"] == "reshard" \
            and modes[1]["gather_bytes"] > 0
    else:
        ok = False
    topo = (agent.history[1].get("topology") or {}) if len(agent.history) > 1 else {}
    ok = ok and topo.get("resume") == "reshard" and topo.get("ckpt_world") == 4 \
        and topo.get("world_size") == new_world and topo.get("prev_world_size") == 4
    # documented envelope: bit-exact before the kill (steps the first,
    # world-4 life completed), RESHARD_LOSS_RTOL after the reshard. The
    # life-1 step interrupted mid-train (kill_at-1) is REPLAYED by the
    # resharded life, so it belongs to the envelope side.
    env_ok, worst = complete, 0.0
    for step in range(total) if complete else ():
        got, want = float.fromhex(losses[step]), float.fromhex(ref[step])
        if step < kill_at - 1:
            env_ok = env_ok and losses[step] == ref[step]
        else:
            rel = abs(got - want) / max(abs(want), 1e-12)
            worst = max(worst, rel)
            env_ok = env_ok and rel <= RESHARD_LOSS_RTOL
    # round-trip leg: resume the final W' checkpoint back at world 4 and
    # compare per-leaf digests — bit-identity through W -> W' -> W
    digests_match = False
    if ok and env_ok:
        ckpt = os.path.join(workdir, name, "ckpt")
        rt_rc, _, _, rt_modes = run_elastic(workdir, name, total, {}, [4],
                                            roundtrip_tag="roundtrip")
        digests_match = (rt_rc == 0 and rt_modes[-1]["mode"] == "reshard"
                         and _manifest_digests(ckpt, f"global_step{total}")
                         == _manifest_digests(ckpt, "roundtrip"))
    ok = ok and env_ok and digests_match
    return _row(f"scale_4_to_{new_world}",
                f"reshard resume + curve in {RESHARD_LOSS_RTOL} envelope + "
                f"W->W'->W digests identical",
                f"rc={rc} modes={[m['mode'] for m in modes]} "
                f"gather={modes[1]['gather_bytes'] if len(modes) > 1 else None} "
                f"worst_rel={worst:.2e} digests_match={digests_match} topo={topo}",
                ok, attempt_topology=topo)


def scenario_scale_up(workdir):
    return scenario_scale(workdir, new_world=8)


def scenario_scale_down(workdir):
    return scenario_scale(workdir, new_world=2)


_SERVE_CHILD = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", os.path.join({repo!r}, ".jax_cache"))
    import numpy as np
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                                 Request, ServingConfig)
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology

    cfg = get_gpt2_config("test", n_layer=2, n_positions=256)
    topo = MeshTopology(tensor=1, data=1, fsdp=1, devices=jax.devices()[:1])
    engine = InferenceEngine(GPT2LMHeadModel(cfg),
                             DeepSpeedInferenceConfig(replace_with_kernel_inject=False),
                             topology=topo)
    sched = ContinuousBatchingScheduler(engine,
                                        ServingConfig(slots=2, prefill_chunk=8))
    rng = np.random.default_rng(0)
    # ~190 warm decode ticks per slot pair: the full serve takes seconds,
    # so the parent's SIGTERM reliably lands mid-flight, while the
    # post-signal drain (<= one request's remaining budget) stays short
    reqs = [Request(prompt=rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32),
                    max_new_tokens=192) for _ in range(8)]
    # warm the serving programs so the post-signal drain measures the drain,
    # not XLA compiles
    warm = Request(prompt=reqs[0].prompt, max_new_tokens=2)
    sched.submit(warm)
    sched.run_until_drained(max_ticks=10**5)
    sched.finished.clear()
    print("SERVING_READY", flush=True)
    rc = sched.serve(reqs)           # installs the PreemptionGuard itself
    stats = sched.stats()
    print("DRAIN " + json.dumps({{
        "rc": rc, "finished": stats["finished"], "refused": stats["refused"],
        "in_flight_after": len(sched.in_flight),
        "pool_used_after": stats["pool"]["used_blocks"],
        "full_budget": all(len(r.output) == r.max_new_tokens
                           for r in sched.finished)}}), flush=True)
    sys.exit(rc)
""")


def scenario_serve_drain(workdir):
    """Real SIGTERM to an actively-serving process (graft-serve): in-flight
    requests must DRAIN to their full token budget (never truncated or
    dropped), everything still queued is terminally refused, no KV block
    leaks, and the process exits 143 so a supervisor reads preemption."""
    import select as _select
    import signal as _signal
    import time as _time

    from envutil import cpu_subprocess_env
    # stderr to a FILE, not a pipe: the parent tails stdout line-by-line
    # before SIGTERM, and an undrained stderr pipe filling up (verbose jax
    # warnings) would deadlock child against parent with no timeout armed
    err_path = os.path.join(workdir, "serve_drain.stderr")
    with open(err_path, "w") as err_fh:
        p = subprocess.Popen([PY, "-c", _SERVE_CHILD.format(repo=REPO)],
                             env=cpu_subprocess_env(), stdout=subprocess.PIPE,
                             stderr=err_fh, text=True, cwd=REPO)
        try:
            deadline = _time.monotonic() + 300
            ready = False
            # read the fd RAW while waiting: select() on the buffered
            # TextIOWrapper can report not-ready while SERVING_READY
            # already sits in the wrapper's internal buffer (a readline
            # drains every line the pipe delivered in one read)
            fd = p.stdout.fileno()
            os.set_blocking(fd, False)
            buf = b""
            while _time.monotonic() < deadline:
                if not _select.select([fd], [], [], 1.0)[0]:
                    continue
                chunk = os.read(fd, 65536)
                if not chunk:
                    break  # EOF: child died before serving
                buf += chunk
                if b"SERVING_READY" in buf:
                    ready = True
                    break
            os.set_blocking(fd, True)  # communicate() needs blocking reads
            if not ready:
                p.kill()
                p.wait(timeout=30)
                err = open(err_path).read()
                return _row("sigterm_mid_serve", "child reaches SERVING_READY",
                            f"never ready in 300s; stderr: {err[-200:]}", False)
            _time.sleep(0.25)        # a few ticks: requests genuinely in flight
            p.send_signal(_signal.SIGTERM)
            out, _ = p.communicate(timeout=420)
        except Exception:
            p.kill()
            raise
    err = open(err_path).read()
    drain = None
    for line in out.splitlines():
        if line.startswith("DRAIN "):
            drain = json.loads(line[len("DRAIN "):])
    if drain is None:
        return _row("sigterm_mid_serve", "drain row emitted",
                    f"rc={p.returncode} no DRAIN line; stderr: {err[-200:]}", False)
    ok = (p.returncode == 143 and drain["rc"] == 143
          and drain["finished"] >= 1 and drain["refused"] >= 1
          and drain["finished"] + drain["refused"] == 8
          and drain["in_flight_after"] == 0 and drain["pool_used_after"] == 0
          and drain["full_budget"])
    return _row("sigterm_mid_serve",
                "in-flight drained (full budget), queued refused, exit 143",
                f"rc={p.returncode} {drain}", ok)


# -- RLHF rollout-loop preemption (graft-rlhf, subprocess) -------------------

# stitched-vs-reference loss envelope (parity with RESHARD_LOSS_RTOL): the
# cohort-aligned config below is observed bit-exact on one host — the rtol
# absorbs cross-platform reduction-order drift only
RLHF_STITCH_LOSS_RTOL = 2e-4

_RLHF_CHILD = textwrap.dedent("""
    import json, os, signal, sys, threading, time
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", os.path.join({repo!r}, ".jax_cache"))
    import jax.numpy as jnp
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import Request, ServingConfig
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology
    from deepspeed_tpu.runtime.resilience.signals import PreemptionGuard
    from deepspeed_tpu.runtime.rlhf import RolloutConfig, RolloutLoop

    CKPT = sys.argv[1]
    FAULT = os.environ.get("RLHF_FB_FAULT") == "1"
    # cohort-aligned config: slots == train_batch_size, uniform budgets,
    # sync_every=1 and align_cohorts=True — every request's entire decode
    # runs under ONE weight generation, so the cohort the drain banks at
    # SIGTERM equals the uninterrupted run's cohort bit-for-bit
    B, TOTAL, PROMPT, NEW = 4, 16, 8, 16

    cfg = get_gpt2_config("test", n_layer=2, n_positions=PROMPT + NEW)

    def loss_fn(logits, batch):
        adv = batch["advantage"]
        mask = batch["mask"].astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logp, batch["rollouts"][:, 1:, None],
                                  axis=-1)[..., 0]
        return -(adv[:, None] * tgt * mask[:, 1:]).sum() / jnp.maximum(
            mask[:, 1:].sum(), 1.0)

    ds = {{"train_batch_size": B,
           "optimizer": {{"type": "AdamW", "params": {{"lr": 1e-4}}}},
           "zero_optimization": {{"stage": 3,
                                  "stage3_param_persistence_threshold": 0}},
           "hybrid_engine": {{"enabled": True, "max_out_tokens": PROMPT + NEW,
                              "inference_tp_size": 1}},
           "steps_per_print": 10**9}}
    # pin to ONE device regardless of any inherited
    # --xla_force_host_platform_device_count (pytest's conftest forces 8):
    # train_batch_size=B must stay whole on one data rank, and the
    # checkpoint layout must be identical across every life
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), config=ds, loss_fn=loss_fn,
        topology=MeshTopology(data=1, fsdp=1, devices=jax.devices()[:1]))

    def pad(pairs, adv):
        width = PROMPT + NEW
        toks = np.zeros((len(pairs), width), np.int32)
        mask = np.zeros((len(pairs), width), np.float32)
        for j, (p, o) in enumerate(pairs):
            seq = np.concatenate([np.asarray(p, np.int32),
                                  np.asarray(o, np.int32)])[:width]
            toks[j, :len(seq)] = seq
            mask[j, len(p):len(seq)] = 1.0
        return {{"input_ids": toks, "rollouts": toks, "advantage": adv,
                 "mask": mask}}

    def make_batch(exps):
        pairs = [(np.asarray(e.prompt, np.int32),
                  np.asarray(e.output, np.int32)) for e in exps]
        reward = np.asarray([(np.asarray(o) % 2 == 0).mean()
                             for _, o in pairs], np.float32)
        return pad(pairs, reward - reward.mean())

    def prompt_fn(i):
        r = np.random.RandomState(1234 + i)
        return Request(prompt=r.randint(0, cfg.vocab_size,
                                        size=(PROMPT,)).astype(np.int32),
                       max_new_tokens=NEW)

    engine.initialize_state(pad([(np.zeros(PROMPT, np.int32),
                                  np.zeros(0, np.int32))] * B,
                                np.zeros(B, np.float32)))
    tag, client_state = engine.resume(CKPT)
    guard = PreemptionGuard().install()
    loop = RolloutLoop(engine, prompt_fn, make_batch,
                       RolloutConfig(train_batch_size=B, total_rollouts=TOTAL,
                                     sync_every=1, checkpoint_dir=CKPT,
                                     align_cohorts=True),
                       serving_config=ServingConfig(slots=B,
                                                    prefill_chunk=PROMPT))
    resumed = loop.restore(client_state)
    if FAULT:
        def _arm():
            # a REAL SIGTERM through the flag-only handler, delivered once
            # the learner has stepped so the stitch spans a train/sync
            # boundary (deterministic landing; the external-delivery path
            # is already proven by sigterm_mid_serve)
            while engine.global_steps < 1:
                time.sleep(0.002)
            os.kill(os.getpid(), signal.SIGTERM)
        threading.Thread(target=_arm, daemon=True).start()
    print("RLHF_READY", flush=True)
    res = loop.run(guard=guard, max_ticks=10**6)
    sync = (res["sync_evidence"] or [{{}}])[-1]
    print("RLHF_EXIT " + json.dumps({{
        "rc": res["exit_code"], "learner_steps": res["learner_steps"],
        "consumed": res["experience_consumed"],
        "banked": res["experience_banked"], "dropped": res["dropped"],
        "drained": res.get("drained", 0),
        "refused": res.get("refused_queued", 0),
        "checkpoint_tag": res.get("checkpoint_tag"), "resumed": resumed,
        "resumed_tag": tag, "sync_generation": res["weight_sync_generation"],
        "gather_bytes": sync.get("gather_bytes"),
        "digest_verified": bool(sync.get("digest")),
        "losses": {{str(r["step"]): float(r["loss"]).hex()
                    for r in res["losses"]}}}}), flush=True)
    sys.exit(res["exit_code"])
""")


def _rlhf_life(workdir, ckpt, fault, name):
    """One child life of the rollout loop; returns (rc, RLHF_EXIT row, stderr)."""
    from envutil import cpu_subprocess_env
    env = cpu_subprocess_env()
    env["RLHF_FB_FAULT"] = "1" if fault else "0"
    err_path = os.path.join(workdir, f"rlhf_{name}.stderr")
    with open(err_path, "w") as err_fh:
        p = subprocess.run([PY, "-c", _RLHF_CHILD.format(repo=REPO), ckpt],
                           env=env, stdout=subprocess.PIPE, stderr=err_fh,
                           text=True, cwd=REPO, timeout=600)
    row = None
    for line in p.stdout.splitlines():
        if line.startswith("RLHF_EXIT "):
            row = json.loads(line[len("RLHF_EXIT "):])
    return p.returncode, row, open(err_path).read()


def scenario_rlhf_sigterm(workdir):
    """SIGTERM mid rollout loop (graft-rlhf): in-flight rollouts must drain
    through the PR-14 path (zero dropped — every one banked as experience),
    the learner checkpoints at one step boundary with the loop cursors in
    client_state, and a resumed life finishes the run with a stitched loss
    curve inside RLHF_STITCH_LOSS_RTOL of an uninterrupted reference."""
    total_steps = 4                      # TOTAL // B in the child
    ckpt = os.path.join(workdir, "rlhf_ckpt")
    rc1, life1, err1 = _rlhf_life(workdir, ckpt, fault=True, name="life1")
    if rc1 != 143 or life1 is None:
        return _row("rlhf_sigterm", "life 1 drains and exits 143",
                    f"rc={rc1} row={life1} stderr: {err1[-200:]}", False)
    rc2, life2, err2 = _rlhf_life(workdir, ckpt, fault=False, name="life2")
    if rc2 != 0 or life2 is None:
        return _row("rlhf_sigterm", "life 2 resumes and finishes",
                    f"rc={rc2} row={life2} stderr: {err2[-200:]}", False)
    rc3, ref, err3 = _rlhf_life(workdir, os.path.join(workdir, "rlhf_ref"),
                                fault=False, name="ref")
    if rc3 != 0 or ref is None:
        return _row("rlhf_sigterm", "uninterrupted reference finishes",
                    f"rc={rc3} row={ref} stderr: {err3[-200:]}", False)
    stitched = dict(life1["losses"])
    stitched.update(life2["losses"])
    worst = float("inf")
    bit_exact = False
    if stitched.keys() == ref["losses"].keys():
        worst, bit_exact = 0.0, True
        for k, ref_hex in ref["losses"].items():
            a, b = float.fromhex(stitched[k]), float.fromhex(ref_hex)
            bit_exact = bit_exact and a == b
            worst = max(worst, abs(a - b) / max(abs(b), 1e-12))
    # life 2's learner_steps is the CUMULATIVE cursor (restored at resume),
    # so it must land exactly on the target; its losses list holds only the
    # steps trained this life and must be disjoint from life 1's
    ok = (life1["dropped"] == 0
          and 1 <= life1["learner_steps"] < total_steps
          and life1["checkpoint_tag"] and life2["resumed"]
          and life2["learner_steps"] == total_steps
          and not set(life1["losses"]) & set(life2["losses"])
          and life1["gather_bytes"] is not None and life1["digest_verified"]
          and worst <= RLHF_STITCH_LOSS_RTOL)
    return _row("rlhf_sigterm",
                "drain zero dropped, exit 143, resumed learner stitches the "
                f"loss curve within rtol {RLHF_STITCH_LOSS_RTOL}",
                f"rc={rc1} steps={life1['learner_steps']}+"
                f"{life2['learner_steps']} dropped={life1['dropped']} "
                f"drained={life1['drained']} refused={life1['refused']} "
                f"banked={life1['banked']} worst_rel={worst:.2e} "
                f"bit_exact={bit_exact}", ok,
                checkpoint_tag=life1["checkpoint_tag"],
                sync_generation=life2["sync_generation"],
                gather_bytes=life1["gather_bytes"])


# -- fleet migration scenarios (graft-fleet, in-process) ---------------------
#
# Deliberately LocalReplica-based: the SIGTERM/SIGKILL paths these assert
# are method calls replaying exactly what fleet/worker.py does on the real
# signals, so the migration/readmission *contracts* are provable with one
# shared engine and zero subprocess compile windows. The real-pipes twin
# lives in tests/unit/inference/test_fleet.py under @pytest.mark.slow.

_FLEET_FIXTURE = None


def _fleet_fixture(n_prompts=6, max_new=12):
    """One tiny inference engine shared by every scheduler (compiled
    programs paid once per process), plus the uninterrupted single-replica
    reference outputs that migration parity is asserted against."""
    global _FLEET_FIXTURE
    if _FLEET_FIXTURE is not None:
        return _FLEET_FIXTURE
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                                 Request, ServingConfig)
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config("test", n_positions=128, dtype=None)
    engine = deepspeed_tpu.init_inference(GPT2LMHeadModel(cfg),
                                          replace_with_kernel_inject=True,
                                          max_out_tokens=128)

    def mk_sched():
        return ContinuousBatchingScheduler(
            engine, ServingConfig(slots=4, prefill_chunk=16, kv_quant=True))

    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, cfg.vocab_size, (24,)).astype(np.int32)
               for _ in range(n_prompts)]
    ref_sched = mk_sched()
    refs = [Request(prompt=p, max_new_tokens=max_new) for p in prompts]
    for r in refs:
        ref_sched.submit(r)
    ref_sched.run_until_drained()
    ref_ttft_p99 = ref_sched.signals()["ttft_p99"]
    _FLEET_FIXTURE = (mk_sched, prompts, [list(r.output) for r in refs],
                      max_new, ref_ttft_p99)
    return _FLEET_FIXTURE


def _fleet_pair(mk_sched):
    from deepspeed_tpu.inference.fleet import FleetRouter, LocalReplica
    router = FleetRouter()
    replicas = {n: LocalReplica(n, mk_sched()) for n in ("r0", "r1")}
    for n, r in replicas.items():
        router.add_replica(n, r)
    return router, replicas


def scenario_replica_sigterm_migrate(workdir):
    """SIGTERM one of two fleet replicas mid-flight: every in-flight
    request's KV must migrate through a digest-verified bundle to the
    peer (capacity overflow re-dispatched, never dropped) and every
    output must be bit-identical to an uninterrupted run."""
    from deepspeed_tpu.runtime.resilience.manifest import (
        CheckpointCorruptError, verify_checkpoint_dir)
    mk_sched, prompts, ref_out, max_new, _ = _fleet_fixture()
    router, replicas = _fleet_pair(mk_sched)
    rids = [router.submit(p, max_new) for p in prompts]
    for _ in range(6):          # genuinely in flight on both replicas
        router.step()
    victim = replicas["r0"]
    inflight_before = len(victim.scheduler.in_flight)
    bundle = os.path.join(workdir, "fleet_sigterm.bundle")
    victim.sigterm(bundle)
    router.run_until_complete(max_rounds=5000)
    st = router.stats()
    try:                         # the published bundle is manifest-verified
        verify_checkpoint_dir(bundle)
        digest = "verified"
    except (CheckpointCorruptError, FileNotFoundError) as e:
        digest = f"corrupt: {str(e)[:80]}"
    parity = all(router.completed[rid]["output"] == ref_out[i]
                 for i, rid in enumerate(rids) if rid in router.completed)
    ok = (st["completed"] == len(prompts) and st["pending"] == 0
          and st["failed"] == 0 and st["duplicate_completions"] == 0
          and inflight_before >= 1 and digest == "verified" and parity)
    return _row("replica_sigterm_migrate",
                "in-flight KV migrated (digest-verified), zero dropped, "
                "greedy parity with uninterrupted run",
                f"{st} in_flight_at_sigterm={inflight_before} "
                f"bundle={digest} parity={parity}", ok,
                migrated=inflight_before)


def scenario_replica_sigterm_shared_prefix(workdir):
    """SIGTERM a replica whose in-flight requests HOLD shared prefix
    blocks (graft-prefix-cache): ref-counted sharing must not leak into
    the bundle — the export materializes each slot's KV rows (bytes, not
    block refs), the bundle digest verifies, and the peer, whose pool
    shares no state with the victim's, continues every request
    bit-identically to an uninterrupted run."""
    import numpy as np
    from deepspeed_tpu.inference.serving import Request
    from deepspeed_tpu.runtime.resilience.manifest import (
        CheckpointCorruptError, verify_checkpoint_dir)
    mk_sched, prompts, _, max_new, _ = _fleet_fixture()
    rng = np.random.default_rng(29)
    template = prompts[0]  # 24 tokens: one full 16-token block shared
    pool_ids = np.concatenate(prompts)
    shared = [np.concatenate([template, rng.choice(pool_ids, 6)])
              .astype(np.int32) for _ in range(6)]
    ref_sched = mk_sched()
    refs = [Request(prompt=p, max_new_tokens=max_new) for p in shared]
    for r in refs:
        ref_sched.submit(r)
    ref_sched.run_until_drained()
    ref_out = [list(r.output) for r in refs]

    router, replicas = _fleet_pair(mk_sched)
    # warm: two requests publish the template's blocks, then retire
    warm_rids = [router.submit(p, max_new) for p in shared[:2]]
    router.run_until_complete(max_rounds=5000)
    # the burst admits against the warm index: prefix affinity routes it
    # to the replica already holding the template's KV
    rids = warm_rids + [router.submit(p, max_new) for p in shared[2:]]
    for _ in range(3):           # genuinely in flight, prefixes restored
        router.step()
    victim = max(replicas.values(), key=lambda r: len(r.scheduler.in_flight))
    shared_held = sum(1 for r in victim.scheduler.in_flight
                      if r.cached_prefix_tokens > 0)
    bundle = os.path.join(workdir, "fleet_sigterm_prefix.bundle")
    victim.sigterm(bundle)
    router.run_until_complete(max_rounds=5000)
    st = router.stats()
    try:
        verify_checkpoint_dir(bundle)
        digest = "verified"
    except (CheckpointCorruptError, FileNotFoundError) as e:
        digest = f"corrupt: {str(e)[:80]}"
    parity = all(router.completed[rid]["output"] == ref_out[i]
                 for i, rid in enumerate(rids) if rid in router.completed)
    ok = (st["completed"] == len(shared) and st["pending"] == 0
          and st["failed"] == 0 and shared_held >= 1
          and digest == "verified" and parity)
    return _row("replica_sigterm_shared_prefix",
                "in-flight requests holding SHARED prefix-cache blocks "
                "migrate digest-verified with greedy parity, zero dropped",
                f"{st} shared_held_at_sigterm={shared_held} "
                f"bundle={digest} parity={parity}", ok,
                migrated=shared_held)


def scenario_replica_sigkill_readmit(workdir):
    """SIGKILL a fleet replica mid-flight: no drain, no bundle — the
    router's liveness sweep must re-admit every orphaned request on the
    peer with at-most-once delivery (duplicates counted, never
    double-delivered), zero dropped, and a bounded TTFT spike."""
    mk_sched, prompts, ref_out, max_new, ref_p99 = _fleet_fixture()
    router, replicas = _fleet_pair(mk_sched)
    rids = [router.submit(p, max_new) for p in prompts]
    for _ in range(4):
        router.step()
    victim = next((r for r in replicas.values()
                   if len(r.scheduler.in_flight)),
                  replicas["r0"])
    victim.sigkill()
    router.run_until_complete(max_rounds=5000)
    st = router.stats()
    parity = all(router.completed[rid]["output"] == ref_out[i]
                 for i, rid in enumerate(rids) if rid in router.completed)
    ttfts = [router.completed[rid]["stats"].get("ttft")
             for rid in router.completed]
    ttft_max = max((t for t in ttfts if t is not None), default=None)
    # re-admitted requests re-run from the prompt, so their TTFT absorbs
    # the time lost to the kill — the spike must stay bounded (a scenario
    # that takes seconds end-to-end, not an unbounded wait), not zero
    ttft_bounded = ttft_max is not None and ttft_max < 30.0
    ok = (st["completed"] == len(prompts) and st["pending"] == 0
          and st["failed"] == 0 and st["readmitted"] >= 1
          and parity and ttft_bounded)
    return _row("replica_sigkill_readmit",
                "orphaned requests re-admitted at-most-once, zero dropped, "
                "bounded TTFT spike, greedy parity",
                f"{st} parity={parity} ttft_max={ttft_max} "
                f"ref_ttft_p99={ref_p99}", ok,
                readmitted=st["readmitted"],
                duplicates=st["duplicate_completions"])


SCENARIOS = {
    "torn_save": scenario_torn_save,
    "serve_drain": scenario_serve_drain,
    "rlhf_sigterm": scenario_rlhf_sigterm,
    "replica_sigterm_migrate": scenario_replica_sigterm_migrate,
    "replica_sigterm_shared_prefix": scenario_replica_sigterm_shared_prefix,
    "replica_sigkill_readmit": scenario_replica_sigkill_readmit,
    "truncate": lambda wd: scenario_corrupt_checkpoint(wd, "truncate"),
    "bitflip": lambda wd: scenario_corrupt_checkpoint(wd, "bitflip"),
    "all_corrupt": scenario_all_corrupt,
    "nan_grads": scenario_overflow_abort,
    "sigkill_resume": scenario_sigkill_resume,
    "http500": scenario_http500_retry,
    "scale_up": scenario_scale_up,
    "scale_down": scenario_scale_down,
}


def main():
    from envutil import pin_cpu_in_process
    pin_cpu_in_process(1)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache"))
    want = [s for s in os.environ.get("FAULT_SCENARIOS",
                                      ",".join(SCENARIOS)).split(",") if s]
    workdir = tempfile.mkdtemp(prefix="fault_bench.")
    print(f"# fault bench: {want} (workdir {workdir})", flush=True)
    failed = 0
    try:
        for name in want:
            try:
                row = SCENARIOS[name](workdir)
            except Exception as e:  # noqa: BLE001 — a crashed scenario is a failed contract
                row = _row(name, "scenario completes", f"crashed: {type(e).__name__}: "
                           f"{str(e)[:200]}", False)
            failed += 0 if row["ok"] else 1
            print(json.dumps(row), flush=True)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    print(f"# DONE ok={len(want) - failed}/{len(want)}", flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
