"""graft-calibrate CLI: fit the static cost model against measured
telemetry and bank/verify the committed calibration artifact.

``fit`` collects samples from accumulated graft-trace runs — telemetry
run dirs, raw ``telemetry.jsonl`` files, or the machine-readable drift
sidecars ``tools/trace_report.py --drift`` writes — groups them per
``<backend>/<scope>`` (training steps and graft-fleet serving ticks fit
side by side), runs the robust least-squares fitter
(deepspeed_tpu/analysis/calibrate.py), and prints the coefficients +
residual evidence. ``--update`` banks the result into
``analysis_results/cost_calibration.json`` (merge semantics — refitting
one scope never drops another's entry).

``verify`` is the R016 contract: exit 1 when the committed artifact is
self-inconsistent (perturbed/hand-edited coefficients — checked
hermetically by refitting the embedded samples), when its jax signature
no longer matches, when the committed search frontier's
``predicted_seconds`` re-rank is stale against the calibration, or —
given telemetry runs as arguments — when fresh residuals drift past
tolerance under the committed coefficients.

Usage:
  python tools/graft_calibrate.py fit runs/a runs/b          # fit + print
  python tools/graft_calibrate.py fit runs/* --update        # bank
  python tools/graft_calibrate.py verify                     # hermetic R016
  python tools/graft_calibrate.py verify runs/*              # + residual drift
"""

import argparse
import json
import os
import sys

# CPU trace-only by design, same bootstrap as graft_lint / graft_search
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_ARTIFACT = os.path.join(REPO, "analysis_results", "cost_calibration.json")
DEFAULT_SEARCH = os.path.join(REPO, "analysis_results", "search_pareto.json")


def _fmt_coeff(v):
    return "unidentified" if v is None else f"{v:.6g}"


def _print_entry(key, entry):
    c, fit = entry["coeffs"], entry["fit"]
    print(f"  {key}: seconds = {_fmt_coeff(c['base_s'])} "
          f"+ {_fmt_coeff(c['s_per_flop'])}·flops_proxy "
          f"+ {_fmt_coeff(c['s_per_byte'])}·bytes_moved")
    print(f"    {fit['samples']} samples, "
          f"median|rel err| {fit.get('median_abs_rel_err', float('nan')):.3f}, "
          f"p90 {fit.get('p90_abs_rel_err', float('nan')):.3f}"
          + (f", clamped: {fit['clamped']}" if fit.get("clamped") else ""))


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graft_calibrate", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("mode", choices=("fit", "verify"))
    ap.add_argument("runs", nargs="*",
                    help="telemetry run dirs, telemetry.jsonl files, or "
                         "trace_report --drift sidecar JSONs")
    ap.add_argument("--update", action="store_true",
                    help="(fit) bank the fitted entries into the committed "
                         "artifact (merge semantics) instead of just printing")
    ap.add_argument("--artifact", default=DEFAULT_ARTIFACT)
    ap.add_argument("--search-pareto", default=DEFAULT_SEARCH,
                    help="(verify) committed frontier to judge the "
                         "predicted_seconds re-rank against")
    ap.add_argument("--min-samples", type=int, default=None,
                    help="(fit) override the fitter's minimum-sample refusal")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="(verify) residual-drift tolerance override")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu import analysis

    log = None if args.quiet else (lambda s: print(f"  {s}", flush=True))

    if args.mode == "fit":
        if not args.runs:
            print("graft-calibrate: fit needs at least one telemetry run",
                  file=sys.stderr)
            return 2
        groups = analysis.collect_samples(args.runs)
        if not groups:
            print("graft-calibrate: no usable samples (runs need a stamped "
                  "static price + drift windows)", file=sys.stderr)
            return 2
        kwargs = {} if args.min_samples is None else \
            {"min_samples": args.min_samples}
        entries, refused = analysis.fit_groups(groups, log=log, **kwargs)
        for key in sorted(entries):
            _print_entry(key, entries[key])
        for key, why in sorted(refused.items()):
            print(f"  {key}: REFUSED — {why}", file=sys.stderr)
        if not entries:
            print("graft-calibrate: every group refused to fit", file=sys.stderr)
            return 1
        if args.update:
            prior = analysis.load_calibration(args.artifact)
            artifact = analysis.calibration_from(entries, prior=prior)
            os.makedirs(os.path.dirname(args.artifact), exist_ok=True)
            with open(args.artifact, "w") as fh:
                json.dump(artifact, fh, indent=2, sort_keys=False)
                fh.write("\n")
            print(f"calibration updated: {os.path.relpath(args.artifact, REPO)} "
                  f"({len(entries)} entr{'y' if len(entries) == 1 else 'ies'} "
                  f"refreshed, {len(artifact['entries'])} total)")
        return 0

    # verify: the R016 contract
    findings = analysis.verify_calibration(
        calibration_path=args.artifact,
        search_pareto_path=args.search_pareto,
        runs=args.runs or None, tolerance=args.tolerance, log=log)
    errors = [f for f in findings if f.severity == analysis.ERROR]
    for f in findings:
        loc = f" @ {f.location}" if f.location else ""
        print(f"  {f.severity:5s} {f.rule} [{f.scenario}]{loc}: {f.message}",
              file=sys.stderr if f.severity == analysis.ERROR else sys.stdout)
    if errors:
        print(f"graft-calibrate: {len(errors)} ERROR finding(s) vs "
              f"{os.path.relpath(args.artifact, REPO)} — refit and re-bank "
              f"with fit --update", file=sys.stderr)
        return 1
    print("graft-calibrate: committed calibration verified clean")
    return 0


if __name__ == "__main__":
    sys.exit(run())
