"""graft-lint CLI: run the static-analysis scenario matrix and gate.

Traces the representative program matrix (deepspeed_tpu/analysis/
scenarios.py) on CPU — no compilation, <2 min — runs every registered
rule (R001..R008, deepspeed_tpu/analysis/rules.py + source_rules.py),
writes ``analysis_results/lint_<sig>.json``, and exits non-zero when a
NEW unwaived ERROR appears relative to the committed baseline
(``analysis_results/baseline.json``). A seeded regression — e.g. forcing
the dense MoE dispatch with ``DS_MOE_ROUTE=dense`` — must fail this
gate; that is the acceptance check.

``--cost`` adds the graft-audit pass (deepspeed_tpu/analysis/cost.py):
per program, a jaxpr-liveness static memory estimate + the three-layer
collective inventory (jaxpr / stablehlo / compiled post-SPMD, with the
backend's own cost/memory analysis as cross-check), rules R009-R012,
and the R013 ratchet against ``analysis_results/cost_baseline.json``
(peak bytes + wire bytes + collective counts per scenario; growth past
tolerance gates). ``--cost --update-baseline`` banks the current costs
(merge semantics — subset runs refresh only their own entries).

Full-matrix ``--cost`` runs additionally re-price every ``gate=True``
graft-search space (deepspeed_tpu/analysis/search.py) and ratchet it
against the committed ``analysis_results/search_pareto.json`` (rule
R014): a drifted candidate set, a committed Pareto winner whose static
price moves >5%, or a winner that is now dominated fails the gate.
``--search`` forces the pass on scenario subsets; ``--no-search`` skips
it; seeded regression: ``DS_LMHEAD_CHUNK=16 python tools/graft_lint.py
--cost`` (the env layer drifts every candidate's traced program, so the
committed winners' prices move and R014 exits 1 — the DS_MOE_ROUTE
pattern). Bank frontier changes with ``tools/graft_search.py --update``,
never here. The same full-matrix runs judge the committed measured-mode
calibration with rule R016 (deepspeed_tpu/analysis/calibrate.py):
perturbed coefficients, a stale jax signature, or a stale
``predicted_seconds`` frontier re-rank vs
``analysis_results/cost_calibration.json`` fail the gate; bank with
``tools/graft_calibrate.py fit --update``.
Seeded cost regressions: ``DS_MOE_ROUTE=dense`` (R009 route-signature
drift + the dense-einsum memory delta), ``DS_PIPE_ACT_BUDGET_MB=2``
on ``pipe_chunked_step`` (R010: the chunked schedule cannot fit the
1F1B activation budget the ``pipe_1f1b_step`` scenario passes), and
``DS_PIPE_SCHEDULE=chunked`` on ``pipe_1f1b_step`` (R009: the program
drifts but the stamped collective signature pins the config-committed
schedule intent — 4 ``collective_permute`` sites vs the drifted 2).

Usage:
  python tools/graft_lint.py                         # full matrix + AST, gate vs baseline
  python tools/graft_lint.py --cost                  # + memory/comms cost pass & ratchet
  python tools/graft_lint.py --scenarios moe_top1_route,moe_top2_route
  python tools/graft_lint.py --update-baseline       # acknowledge current ERRORs
  python tools/graft_lint.py --no-ast | --ast-only
  python tools/graft_lint.py --list                  # rule + scenario inventory

Waivers: ``analysis_results/waivers.json`` — a list of
``{"rule": "R003", "scenario": "train_batch*", "match": "...", "reason": "..."}``
entries — plus inline ``# graft-lint: waive R008 <reason>`` comments for
the AST rule. Waived findings report but never gate; waivers that match
NO current finding are reported as stale (WARN) so dead entries get
pruned.

``GRAFT_LINT_DEVICES=16`` raises the forced host-device count so the
16-virtual-device composition scenario can attempt its trace.
"""

import argparse
import ast
import json
import os
import sys

# CPU + a multi-device host mesh BEFORE jax initializes: the matrix
# includes multi-device programs (same bootstrap as tests/conftest.py).
# GRAFT_LINT_DEVICES overrides the count for the 16-device composition.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
_n_dev = os.environ.get("GRAFT_LINT_DEVICES", "8")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={_n_dev}").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: source roots the AST rule sweeps
AST_ROOTS = ("deepspeed_tpu", "tools", "bench.py", "envutil.py")


def collect_source_files(repo=REPO, roots=AST_ROOTS):
    files = []
    for root in roots:
        path = os.path.join(repo, root)
        if os.path.isfile(path):
            paths = [path]
        else:
            paths = [os.path.join(dp, f) for dp, _, fs in os.walk(path)
                     for f in fs if f.endswith(".py")]
        for p in sorted(paths):
            rel = os.path.relpath(p, repo)
            try:
                with open(p) as fh:
                    src = fh.read()
                files.append((rel, src, ast.parse(src, filename=rel)))
            except SyntaxError as e:  # a broken file is its own finding
                print(f"graft-lint: cannot parse {rel}: {e}", file=sys.stderr)
    return files


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graft_lint", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--scenarios", default=None,
                    help="comma list of scenario names (default: all)")
    ap.add_argument("--baseline", default=os.path.join(REPO, "analysis_results", "baseline.json"))
    ap.add_argument("--waivers", default=os.path.join(REPO, "analysis_results", "waivers.json"))
    ap.add_argument("--out", default=os.path.join(REPO, "analysis_results"))
    ap.add_argument("--update-baseline", action="store_true",
                    help="acknowledge every current ERROR into the baseline and exit 0 "
                         "(with --cost: also bank current costs into the cost baseline)")
    ap.add_argument("--cost", action="store_true",
                    help="run the graft-audit cost pass: static memory + collective "
                         "inventory, rules R009-R013, ratchet vs the cost baseline")
    ap.add_argument("--cost-baseline",
                    default=os.path.join(REPO, "analysis_results", "cost_baseline.json"))
    ap.add_argument("--no-compile", action="store_true",
                    help="with --cost: skip compiling programs (no post-SPMD "
                         "collective layer / backend cross-check; trace-only)")
    ap.add_argument("--no-ast", action="store_true", help="skip the source AST pass")
    ap.add_argument("--ast-only", action="store_true", help="run ONLY the source AST pass")
    ap.add_argument("--search", action="store_true",
                    help="with --cost: run the R014 search-frontier gate even on a "
                         "--scenarios subset (default: full-matrix runs only)")
    ap.add_argument("--no-search", action="store_true",
                    help="with --cost: skip the R014 search-frontier gate")
    ap.add_argument("--search-pareto",
                    default=os.path.join(REPO, "analysis_results", "search_pareto.json"))
    ap.add_argument("--cost-calibration",
                    default=os.path.join(REPO, "analysis_results",
                                         "cost_calibration.json"))
    ap.add_argument("--list", action="store_true", help="print rules + scenarios and exit")
    ap.add_argument("--rules-md", action="store_true",
                    help="print the README rule table generated from the rule "
                         "registry and exit (keeps docs from drifting behind new rules)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    import jax

    # env vars alone don't switch backends when a sitecustomize has pinned
    # jax_platforms (e.g. the axon TPU tunnel) — re-pin in config. The lint
    # matrix is trace-only and CPU by design; never burn chip time on it.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu import analysis
    from deepspeed_tpu.analysis import scenarios as scen

    if args.rules_md:
        print(analysis.rules_markdown())
        return 0

    if args.list:
        # generated from the registry — a newly registered rule (e.g. R014)
        # appears here with zero doc edits; same source as --rules-md
        print("rules:")
        for r in sorted(analysis.RULES.values(), key=lambda r: r.id):
            print(f"  {r.id}  [{r.severity:5s} {r.layer:5s}] {r.title}")
        print("scenarios:")
        for name in scen.SCENARIOS:
            print(f"  {name}")
        print("search spaces (analysis/search.py; R014 gates gate=True spaces):")
        for name, space in analysis.SPACES.items():
            n = len(analysis.enumerate_candidates(space))
            print(f"  {name}  [{n} candidates{' gate' if space.gate else ''}]")
        print("cost metrics (per program, --cost):")
        print("  peak_bytes / peak_transient_bytes  static liveness estimate (analysis/memory.py)")
        print("  bytes_moved{jaxpr,stablehlo,compiled}  analytic wire bytes (analysis/hlo_cost.py)")
        print("  collective counts per layer+kind   ratcheted by R013 vs cost_baseline.json")
        print("  frontier winners + price drift     ratcheted by R014 vs search_pareto.json")
        print("  calibrated seconds + residual fit  ratcheted by R016 vs cost_calibration.json")
        return 0

    # ---- program layer -------------------------------------------------
    per_program, skipped, cost_by_program = {}, {}, {}
    if not args.ast_only:
        names = args.scenarios.split(",") if args.scenarios else None
        programs, skipped = scen.build(names)
        for info in programs:
            analyzer = analysis.ProgramAnalyzer(info)
            findings, metrics = analysis.run_program_rules(info, analyzer=analyzer)
            if args.cost:
                cost = analysis.build_cost(info, analyzer=analyzer,
                                           compile=not args.no_compile)
                findings.extend(analysis.run_cost_rules(info, cost, analyzer))
                cost_by_program[info.name] = cost
            per_program[info.name] = (findings, metrics)
            if not args.quiet:
                s = analysis.summarize(findings)
                line = (f"  {info.name:24s} rules_hit={s['rule_hits'] or '{}'} "
                        f"errors={s['errors']}")
                if args.cost:
                    cost = cost_by_program[info.name]
                    line += (f" peak={cost.memory.peak_bytes / 2**20:.1f}MiB "
                             f"transient={cost.memory.peak_transient_bytes / 2**20:.1f}MiB "
                             f"comms={cost.bytes_moved()}")
                print(line)
        for name, gap in skipped.items():
            print(f"  {name:24s} SKIPPED [{gap['kind']}]: {gap['detail']}")

    # ---- source layer --------------------------------------------------
    ast_findings = []
    if not args.no_ast:
        files = collect_source_files()
        for rule in analysis.ast_rules():
            ast_findings.extend(rule.check(files))
        if not args.quiet:
            s = analysis.summarize(ast_findings)
            print(f"  {'<source AST>':24s} rules_hit={s['rule_hits'] or '{}'} "
                  f"errors={s['errors']} waived={s['waived']}")

    # ---- cost ratchet (R013) -------------------------------------------
    cost_baseline = None
    if args.cost and not args.ast_only:
        cost_baseline = analysis.load_cost_baseline(args.cost_baseline)
        if not args.update_baseline:
            ratchet = analysis.r013_cost_ratchet(cost_by_program, cost_baseline)
            for f in ratchet:
                fs, metrics = per_program.setdefault(f.scenario, ([], {}))
                fs.append(f)

    # ---- search-frontier ratchet (R014) --------------------------------
    # full-matrix --cost runs re-price the gate spaces against the
    # committed Pareto artifact; subset runs skip (their scenario list was
    # scoped on purpose) unless --search forces it. Banking happens in
    # tools/graft_search.py --update, never via --update-baseline.
    if (args.cost and not args.ast_only and not args.no_search
            and not args.update_baseline
            and (args.scenarios is None or args.search)):
        for f in analysis.verify_spaces(
                args.search_pareto,
                log=(None if args.quiet else lambda s: print(f"  [search]{s}"))):
            fs, metrics = per_program.setdefault(f.scenario, ([], {}))
            fs.append(f)
        # R016: the calibration artifact's own ratchet — hermetic
        # self-consistency + the frontier's predicted_seconds re-rank
        # provenance against the committed cost_calibration.json. Banking
        # happens in tools/graft_calibrate.py fit --update, never here.
        for f in analysis.verify_calibration(
                calibration_path=args.cost_calibration,
                search_pareto_path=args.search_pareto):
            fs, metrics = per_program.setdefault(f.scenario, ([], {}))
            fs.append(f)

    # ---- waivers -------------------------------------------------------
    waiver_entries = []
    if os.path.exists(args.waivers):
        with open(args.waivers) as fh:
            waiver_entries = json.load(fh)
    waivers = analysis.load_waivers(waiver_entries)
    all_findings = [f for fs, _ in per_program.values() for f in fs] + ast_findings
    analysis.apply_waivers(all_findings, waivers)

    # ---- stale waivers (WARN, never gating) ----------------------------
    # config waivers are judged only on full-matrix program runs (a subset
    # run legitimately produces no findings for the scenarios it skipped);
    # inline waivers are judged whenever the AST pass swept all files
    stale = []
    if not args.ast_only and args.scenarios is None:
        from deepspeed_tpu.analysis.core import stale_config_waivers
        for w in stale_config_waivers(all_findings, waivers):
            stale.append({"kind": "config", "rule": w.rule, "scenario": w.scenario,
                          "match": w.match, "reason": w.reason})
    if not args.no_ast:
        from deepspeed_tpu.analysis.source_rules import stale_inline_waivers
        stale.extend(stale_inline_waivers(files, ast_findings))
    for s in stale:
        where = (f"{s['file']}:{s['line']}" if s["kind"] == "inline"
                 else f"{s['rule']}/{s['scenario']}")
        print(f"graft-lint: WARN stale waiver [{s['kind']}] {where} matches no "
              f"current finding — prune it", file=sys.stderr)

    # ---- report --------------------------------------------------------
    sig = analysis.matrix_signature(list(per_program) + (["ast"] if not args.no_ast else []))
    report = analysis.build_report(per_program, ast_findings, skipped=skipped,
                                   waivers_in_effect=waiver_entries,
                                   cost_by_program=cost_by_program if args.cost else None,
                                   stale_waivers=stale)
    path = analysis.write_report(report, args.out, sig)
    if not args.quiet:
        print(f"report: {os.path.relpath(path, REPO)}")

    # ---- gate ----------------------------------------------------------
    if args.update_baseline:
        baseline = analysis.baseline_from(all_findings)
        os.makedirs(os.path.dirname(args.baseline), exist_ok=True)
        with open(args.baseline, "w") as fh:
            json.dump(baseline, fh, indent=2)
            fh.write("\n")
        print(f"baseline updated: {os.path.relpath(args.baseline, REPO)} "
              f"({len(baseline['fingerprints'])} acknowledged ERRORs)")
        if args.cost and cost_by_program:
            new_cost = analysis.cost_baseline_from(cost_by_program, prior=cost_baseline)
            with open(args.cost_baseline, "w") as fh:
                json.dump(new_cost, fh, indent=2)
                fh.write("\n")
            print(f"cost baseline updated: {os.path.relpath(args.cost_baseline, REPO)} "
                  f"({len(cost_by_program)} program(s) refreshed, "
                  f"{len(new_cost['programs'])} total)")
        return 0

    baseline = analysis.load_baseline(args.baseline)
    fresh = analysis.new_errors(all_findings, baseline)
    if fresh:
        print(f"graft-lint: {len(fresh)} NEW ERROR finding(s) vs baseline "
              f"{os.path.relpath(args.baseline, REPO)}:", file=sys.stderr)
        for f in fresh:
            loc = f" @ {f.location}" if f.location else ""
            print(f"  {f.rule} [{f.scenario}]{loc}: {f.message}", file=sys.stderr)
        return 1
    unwaived_warns = sum(1 for f in all_findings
                         if not f.waived and f.severity == analysis.WARN)
    if not args.quiet:
        print(f"graft-lint: clean vs baseline "
              f"({len(all_findings)} findings: "
              f"{sum(1 for f in all_findings if f.waived)} waived, "
              f"{unwaived_warns} warn)")
    return 0


if __name__ == "__main__":
    sys.exit(run())
