"""graft-search CLI: enumerate + statically price program candidates and
commit the Pareto frontier.

Runs the declared candidate spaces (deepspeed_tpu/analysis/search.py) —
remat policy at block boundaries, LM-head loss/grad chunk sizes, QKV /
attention-output projection fusion, optimizer-fusion variants — through
the REAL engine knobs (the "program" config block +
``optimizer.legacy_fusion``), prices every candidate from its traced
jaxpr alone (peak transient bytes, analytic wire bytes, a trip-count-
weighted dot-FLOP proxy; no lowering, no compilation), and prints the
frontier with full dominated-candidate provenance. The judged 350M space
(26 candidates) prices in a few minutes on the 1-core CPU rig.

Default mode verifies against the committed
``analysis_results/search_pareto.json`` (the R014 contract: exit 1 on
candidate-set drift, winner price drift >5%, or a dominated committed
winner); ``--update`` banks the current results instead (merge semantics
— a single-space update never drops another space's entry).
perf_ladder.py generates ``350m_search_*`` rungs from the committed
frontier, so the next chip window measures exactly the statically-
surviving set.

Usage:
  python tools/graft_search.py                          # price + verify all spaces
  python tools/graft_search.py --spaces gpt2_test_gate  # subset
  python tools/graft_search.py --update                 # bank the frontier
"""

import argparse
import json
import os
import sys
import time

# CPU trace-only by design, same bootstrap as graft_lint (prices must
# never depend on an accelerator being attached, or on its device count —
# spaces pin a 1-device topology regardless)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_ARTIFACT = os.path.join(REPO, "analysis_results", "search_pareto.json")


def _fmt_bytes(n):
    return f"{n / 2**20:8.1f}M"


def _print_space(name, result, quiet=False):
    cands = result["candidates"]
    frontier = set(result["frontier"])
    calibrated = "predicted_seconds" in result["objectives"]
    print(f"space {name}: {len(cands)} candidates, "
          f"{len(frontier)} on the frontier "
          f"(objectives: {', '.join(result['objectives'])})")
    if quiet:
        return
    sec_hdr = f" {'pred-sec':>9s}" if calibrated else ""
    header = (f"  {'':1s} {'candidate':58s} {'transient':>9s} {'comms':>9s} "
              f"{'dot-TFLOP':>9s}{sec_hdr}")
    print(header)
    for cid, entry in cands.items():
        m = entry["metrics"]
        mark = "*" if cid in frontier else " "
        dom = ("" if cid in frontier
               else f"  << {entry.get('dominated_by', ['?'])[0]}")
        sec = (f" {m['predicted_seconds']:9.4f}" if calibrated else "")
        print(f"  {mark} {cid:58s} {_fmt_bytes(m['peak_transient_bytes'])} "
              f"{_fmt_bytes(m['bytes_moved'])} {m['flops_proxy'] / 1e12:9.3f}"
              f"{sec}{dom}")
    if calibrated and result.get("seconds_rank"):
        key = (result.get("calibration") or {}).get("key")
        print(f"  frontier in calibrated seconds ({key}):")
        for i, cid in enumerate(result["seconds_rank"]):
            sec = cands[cid]["metrics"]["predicted_seconds"]
            print(f"    #{i + 1} {cid} ({sec:.4f}s)")


def run(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graft_search", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--spaces", default=None,
                    help="comma list of space names (default: all declared)")
    ap.add_argument("--update", action="store_true",
                    help="bank the current results into the committed artifact "
                         "(merge semantics) instead of verifying against it")
    ap.add_argument("--artifact", default=DEFAULT_ARTIFACT)
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    import jax
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")

    from deepspeed_tpu import analysis

    names = (args.spaces.split(",") if args.spaces else list(analysis.SPACES))
    unknown = [n for n in names if n not in analysis.SPACES]
    if unknown:
        print(f"graft-search: unknown space(s) {unknown}; "
              f"valid: {sorted(analysis.SPACES)}", file=sys.stderr)
        return 2

    # the committed calibration (if banked) adds the predicted_seconds
    # objective + seconds_rank to every priced space
    calibration = analysis.load_calibration()

    results = {}
    for name in names:
        t0 = time.time()
        log = None if args.quiet else (lambda s: print(f"  {s}", flush=True))
        if not args.quiet:
            n = len(analysis.enumerate_candidates(analysis.SPACES[name]))
            print(f"# pricing {name} ({n} candidates)...", flush=True)
        results[name] = analysis.run_space(name, log=log, calibration=calibration)
        if not args.quiet:
            print(f"# {name} priced in {time.time() - t0:.1f}s", flush=True)
        _print_space(name, results[name], quiet=args.quiet)

    if args.update:
        prior = analysis.load_search_artifact(args.artifact)
        artifact = analysis.search_artifact_from(results, prior=prior)
        os.makedirs(os.path.dirname(args.artifact), exist_ok=True)
        with open(args.artifact, "w") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=False)
            fh.write("\n")
        print(f"search artifact updated: {os.path.relpath(args.artifact, REPO)} "
              f"({len(results)} space(s) refreshed, "
              f"{len(artifact['spaces'])} total)")
        return 0

    # verify mode: the R014 contract against the committed artifact
    artifact = analysis.load_search_artifact(args.artifact)
    findings = analysis.r014_search_frontier(artifact, results)
    errors = [f for f in findings if f.severity == analysis.ERROR]
    for f in findings:
        loc = f" @ {f.location}" if f.location else ""
        print(f"  {f.severity:5s} {f.rule} [{f.scenario}]{loc}: {f.message}",
              file=sys.stderr if f.severity == analysis.ERROR else sys.stdout)
    if errors:
        print(f"graft-search: {len(errors)} ERROR finding(s) vs "
              f"{os.path.relpath(args.artifact, REPO)} — fix the drift or bank "
              f"with --update", file=sys.stderr)
        return 1
    print("graft-search: committed frontier verified clean")
    return 0


if __name__ == "__main__":
    sys.exit(run())
