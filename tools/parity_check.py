"""Cross-backend loss-curve parity harness (BASELINE.md north star:
"bit-identical loss curves vs CPU reference").

``curve()`` trains a small GPT-2 for N steps under conditions chosen to be
backend-reproducible — fp32 params AND fp32 compute, ``highest`` matmul
precision (on TPU this forces the 6-pass fp32 matmul instead of bf16
passes), deterministic seeded data, no dropout — and returns the per-step
losses as exact bit patterns (fp32 hex), so comparison is free of
print-precision noise.

``compare()`` reports bit-identity, max |Δ|, and max ULP distance between
two curves. bench.py attaches this to its JSON when it measures on a live
accelerator (the CPU reference curve computed in a scrubbed subprocess);
``PARITY_MAX_ULP`` is the enforcement envelope — 0 (default) demands
bit-identity, a positive value pins the measured-and-documented envelope.

Reference-pinning caveat (measured): XLA:CPU splits its compute threads
per virtual device, and thread partitioning changes matmul reduction
order — an 8-virtual-device process drifts ~1 ULP/step from a 1-device
process on the SAME machine. The CPU reference is therefore always run at
exactly ONE pinned CPU device (bench.py passes
``cpu_subprocess_env(n_virtual_devices=1)``); with that pinned, curves
are bit-reproducible across processes (test_loss_parity).

Run directly: ``python tools/parity_check.py`` → one JSON line
{"backend", "curve_hex"}.
"""

import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = int(os.environ.get("PARITY_STEPS", "8"))
SEED = int(os.environ.get("PARITY_SEED", "0"))


def curve(steps: int = STEPS, seed: int = SEED):
    """Per-step fp32 losses for the reproducible config, as float values."""
    import jax

    jax.config.update("jax_default_matmul_precision", "highest")
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JAX_CACHE_DIR", os.path.join(
                              os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                              ".jax_cache")))
    except Exception:
        pass

    import numpy as np
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
    from deepspeed_tpu.parallel.topology import MeshTopology

    cfg = get_gpt2_config("test", n_layer=2, n_embd=64, n_head=4, n_positions=64,
                          dropout=0.0, dtype=jnp.float32)
    model = GPT2LMHeadModel(cfg)
    # the WORKLOAD must not depend on jax.device_count(): a 4-chip slice and
    # the 1-CPU reference must train the same batches through the same
    # program, so the curve is pinned to ONE device regardless of backend
    topo = MeshTopology(data=1, devices=jax.devices()[:1])
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, topology=topo,
        config={"train_batch_size": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "gradient_clipping": 1.0,
                "zero_optimization": {"stage": 0},
                "steps_per_print": 10**9})
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(steps):
        batch = {"input_ids": rng.integers(0, cfg.vocab_size,
                                           (4, 64)).astype(np.int32)}
        loss = engine.train_batch(batch)
        losses.append(np.float32(np.asarray(jax.device_get(loss))))
    return [float(l) for l in losses]


def to_hex(values):
    return [format(struct.unpack(">I", struct.pack(">f", float(v)))[0], "08x")
            for v in values]


def from_hex(hexes):
    return [struct.unpack(">f", struct.pack(">I", int(h, 16)))[0] for h in hexes]


def _ulp_distance(a: float, b: float) -> int:
    """ULP distance between two fp32 values (monotone integer mapping)."""
    def key(x):
        (i,) = struct.unpack(">i", struct.pack(">f", float(x)))
        return i if i >= 0 else -(i & 0x7FFFFFFF)
    return abs(key(a) - key(b))


def compare(curve_a, curve_b):
    """Parity report between two same-length fp32 loss curves."""
    assert len(curve_a) == len(curve_b), (len(curve_a), len(curve_b))
    diffs = [abs(a - b) for a, b in zip(curve_a, curve_b)]
    ulps = [_ulp_distance(a, b) for a, b in zip(curve_a, curve_b)]
    return {
        "steps": len(curve_a),
        "bit_identical": all(u == 0 for u in ulps),
        "max_abs_diff": max(diffs) if diffs else 0.0,
        "max_ulp": max(ulps) if ulps else 0,
    }


def precision_attribution():
    """R002's per-(src->dst, scope) upcast tally for the parity program —
    the graft-lint metric that tells the ROADMAP-4 ULP hunt *where* the
    numerics widen. Surfacing it here means the hunt reads ONE report:
    the curve and its attribution come from the same tool invocation
    instead of cross-referencing a separate lint run. Trace-only (a
    couple of seconds next to the training steps); any failure degrades
    to an error string rather than killing the curve.
    ``PARITY_ATTRIBUTION=0`` opts out."""
    if os.environ.get("PARITY_ATTRIBUTION", "1") != "1":
        return None
    try:
        from deepspeed_tpu.analysis import run_program_rules
        from deepspeed_tpu.analysis import scenarios as scen

        info = scen.SCENARIOS["train_batch_parity"]()
        _, metrics = run_program_rules(info, rules=["R002"])
        return metrics.get("precision_attribution", {})
    except Exception as e:  # noqa: BLE001 — evidence must never kill the curve
        return {"error": f"{type(e).__name__}: {str(e)[:160]}"}


def main():
    import jax
    vals = curve()
    out = {"backend": jax.default_backend(),
           "curve_hex": to_hex(vals),
           "curve": [round(v, 6) for v in vals]}
    attribution = precision_attribution()
    if attribution is not None:
        out["precision_attribution"] = attribution
    print(json.dumps(out))


if __name__ == "__main__":
    main()
