"""Perf bisect: time the pieces of the 350M train step on the real chip.

Run: python tools/perf_bisect.py [piece ...]
Pieces: fwd fwdnoloss bwd bwd32 opt nolmhead
Each prints one line: <piece> <ms>
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
from deepspeed_tpu.models.gpt2 import cross_entropy_loss

MB = int(os.environ.get("BENCH_MICRO_BS", "4"))
SEQ = int(os.environ.get("BENCH_SEQ", "1024"))
STEPS = int(os.environ.get("BENCH_STEPS", "10"))
MODEL = os.environ.get("BENCH_MODEL", "350m")
REMAT = os.environ.get("BENCH_REMAT", "1") == "1"
ATTN = os.environ.get("BENCH_ATTN", "flash")


def timed(fn, *args):
    """Time STEPS sequential executions with a forced data dependency (the
    tunneled backend appears to dedupe identical (program, args) dispatches,
    so same-arg loops report impossibly fast times)."""
    ids = args[-1]
    head = args[:-1]

    def chained(carry, ids):
        out = fn(*head, jnp.bitwise_xor(ids, carry.astype(jnp.int32) & 0))
        # fold the (scalar or tree) output back into the next call's ids
        s = sum(jnp.sum(l.astype(jnp.float32)) for l in jax.tree.leaves(out))
        return carry + s, out  # carry grows → every call has distinct args
    cf = jax.jit(chained)
    carry = jnp.float32(0)
    out = cf(carry, ids)
    jax.block_until_ready(out)
    t0 = time.time()
    carry = jnp.float32(0)
    for _ in range(STEPS):
        carry, out = cf(carry, ids)
    jax.block_until_ready(carry)
    return (time.time() - t0) / STEPS * 1e3


def main():
    pieces = sys.argv[1:] or ["fwd", "bwd", "opt"]
    cfg = get_gpt2_config(MODEL, n_positions=SEQ, remat=REMAT,
                          attention_backend=ATTN, dtype=jnp.bfloat16)
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (MB, SEQ)), jnp.int32)
    params = jax.jit(lambda k: model.init(k, ids[:1, :8])["params"])(jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    print(f"# params={n_params/1e6:.1f}M mb={MB} seq={SEQ} remat={REMAT} attn={ATTN}", flush=True)

    bf16_params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)

    def loss_fn(p, ids):
        logits = model.apply({"params": p}, ids)
        labels = jnp.concatenate([ids[:, 1:], jnp.full((ids.shape[0], 1), -100, jnp.int32)], axis=1)
        return cross_entropy_loss(logits, labels)

    if "fwd" in pieces:
        f = jax.jit(loss_fn)
        print(f"fwd {timed(f, bf16_params, ids):.1f}", flush=True)

    if "fwdnoloss" in pieces:
        f = jax.jit(lambda p, i: model.apply({"params": p}, i).astype(jnp.float32).mean())
        print(f"fwdnoloss {timed(f, bf16_params, ids):.1f}", flush=True)

    if "bwd" in pieces:
        g = jax.jit(lambda p, i: jax.grad(loss_fn)(p, i))
        print(f"bwd {timed(g, bf16_params, ids):.1f}", flush=True)

    if "bwd32" in pieces:
        # grads computed from fp32 masters with cast inside (engine layout)
        def loss32(p, i):
            cp = jax.tree.map(lambda x: x.astype(jnp.bfloat16), p)
            return loss_fn(cp, i)
        g = jax.jit(lambda p, i: jax.grad(loss32)(p, i))
        print(f"bwd32 {timed(g, params, ids):.1f}", flush=True)

    if "opt" in pieces:
        tx = optax.adamw(1e-4, weight_decay=0.01)
        opt_state = jax.jit(tx.init)(params)
        grads = jax.tree.map(lambda p: jnp.ones_like(p), params)

        def step(p, s, g):
            u, s2 = tx.update(g, s, p)
            return optax.apply_updates(p, u), s2
        f = jax.jit(step, donate_argnums=(0, 1))
        # no donation-safe repeat timing with donated bufs; time one-shot loop
        out = f(params, opt_state, grads)
        jax.block_until_ready(out)
        p2, s2 = out
        t0 = time.time()
        for _ in range(STEPS):
            p2, s2 = f(p2, s2, grads)
        jax.block_until_ready(p2)
        print(f"opt {(time.time() - t0) / STEPS * 1e3:.1f}", flush=True)

    if "nolmhead" in pieces:
        def loss_nolm(p, i):
            # model forward but reduce hidden states instead of logits
            # (monkey: call apply with capture of pre-head sum via aux) —
            # cheapest proxy: mean of logits at bf16 without CE
            logits = model.apply({"params": p}, i)
            return logits.astype(jnp.float32).mean()
        f = jax.jit(lambda p, i: jax.grad(loss_nolm)(p, i))
        print(f"bwd_nolosshead {timed(f, bf16_params, ids):.1f}", flush=True)


if __name__ == "__main__":
    main()
