"""One clean-exit TPU breakdown: times fwd, fwd+bwd, and the full engine
step as separate compiled programs, each iterated with CHAINED data
dependencies (output feeds next input) so the axon tunnel's identical-
dispatch dedupe can't fake the numbers. Attribution without
jax.profiler.trace (a killed trace session wedges the tunnel).

Run: python tools/perf_breakdown.py   (background it; poll stdout —
NEVER wrap in `timeout`: a killed TPU process wedges the tunnel claim)

MoE mode (``BENCH_MOE=1``): instead of the engine step, attribute one MoE
layer's time into gate / dispatch / expert-matmul / combine sections by
timing nested prefix programs (gate; gate+dispatch; +experts; +combine)
per route, so the dense-vs-sorted A/B is visible per phase, not just
end-to-end. Defaults to the 125m_moe8 shape (M=768, E=8, mb=8, seq=1024 —
override via BENCH_MOE_DIM/EXPERTS/BENCH_MICRO_BS/BENCH_SEQ/BENCH_MOE_K/
BENCH_MOE_CF); routes from BENCH_MOE_ROUTES (default "dense,sorted").
Each route row also reports ``dispatch_peak_bytes`` — the routing
metadata + dispatch buffers the route materializes (the dense route's
[S,E,C] tensors vs the sorted route's [S*k] index vectors).

Pipe mode (``BENCH_PIPE=1``): the pipeline-schedule A/B — times
``train_batch`` per schedule (BENCH_PIPE_SCHEDULES, default
"1f1b,chunked,gpipe") on a pipe-only mesh (BENCH_PIPE_STAGES=4,
BENCH_PIPE_MICROS=16, BENCH_MICRO_BS=2, BENCH_SEQ=128,
BENCH_PIPE_EMBD=128, BENCH_PIPE_MODEL=test) and stamps each row with the
schedule's STATIC transient-bytes estimate (analysis.cost_engine_program,
trace-only) so the measured step time rides next to the activation bound
R010 gates — the PERF.md §PR11 table regenerates from these rows.
"""
import json
import os
import sys
import time

# BENCH_DEVICES=N forces a virtual host-device count (the pipe A/B needs
# a pipe mesh on CPU); must land in XLA_FLAGS before jax imports.
_n_dev = os.environ.get("BENCH_DEVICES")
if _n_dev and "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={_n_dev}").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import jax
import jax.numpy as jnp

from bench_core import enable_compile_cache

enable_compile_cache()

import deepspeed_tpu
from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

MODEL = os.environ.get("BENCH_MODEL", "350m")
MB = int(os.environ.get("BENCH_MICRO_BS", "4"))
SEQ = int(os.environ.get("BENCH_SEQ", "1024"))
N = 10


def timed(tag, fn, carry):
    """fn: carry -> carry with chained deps. Times N iterations."""
    carry = fn(carry)  # warmup (compile)
    jax.block_until_ready(carry)
    t0 = time.time()
    for _ in range(N):
        carry = fn(carry)
    jax.block_until_ready(carry)
    dt = (time.time() - t0) / N
    print(json.dumps({"tag": tag, "ms": round(dt * 1e3, 1)}), flush=True)
    return dt


def moe_sections():
    """Per-phase MoE attribution: nested prefix programs per route. Chained
    deps (loss-derived zero shift) keep the dedupe honest, same as the
    model-level sections."""
    import jax.nn
    from deepspeed_tpu.moe.sharded_moe import _capacity, top1gating, top1routing, top2gating, top2routing
    from deepspeed_tpu.ops.pallas.moe_dispatch import inverse_index, permute_rows, resolve_impl

    M = int(os.environ.get("BENCH_MOE_DIM", "768"))       # 125m n_embd
    E = int(os.environ.get("BENCH_MOE_EXPERTS", "8"))
    K = int(os.environ.get("BENCH_MOE_K", "1"))
    CF = float(os.environ.get("BENCH_MOE_CF", "1.25"))
    S = MB * SEQ                                          # tokens per group (G=1)
    F = 4 * M
    C = _capacity(S, E, (2 * CF) if K == 2 else CF, 4)
    impl = resolve_impl(os.environ.get("DS_MOE_KERNEL", "auto"))
    routes = os.environ.get("BENCH_MOE_ROUTES", "dense,sorted").split(",")
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    print(f"# moe breakdown M={M} E={E} k={K} cf={CF} S={S} C={C} "
          f"impl={impl} dtype={dt.__name__}", flush=True)

    rng = np.random.default_rng(0)
    wg = jnp.asarray(rng.normal(0, 0.02, (M, E)), jnp.float32)
    w1 = jnp.asarray(rng.normal(0, 0.02, (E, M, F)), dt)
    w2 = jnp.asarray(rng.normal(0, 0.02, (E, F, M)), dt)
    tokens0 = jnp.asarray(rng.normal(size=(S, M)), dt)
    itemsize = jnp.dtype(dt).itemsize

    def gate_dense(tok):
        logits = tok.astype(jnp.float32) @ wg
        if K == 2:
            return top2gating(logits, CF, 4)
        return top1gating(logits, CF, 4)

    def gate_sorted(tok):
        logits = tok.astype(jnp.float32) @ wg
        if K == 2:
            return top2routing(logits, CF, 4)
        return top1routing(logits, CF, 4)

    def dispatch_dense(tok):
        l_aux, combine, dispatch, _ = gate_dense(tok)
        return jnp.einsum("sec,sm->ecm", dispatch.astype(tok.dtype), tok), combine, l_aux

    def dispatch_sorted(tok):
        l_aux, rt, _ = gate_sorted(tok)
        flat_slot = jnp.where(rt.keep > 0, rt.expert * C + rt.slot,
                              E * C).astype(jnp.int32).reshape(1, S * K)
        src = inverse_index(flat_slot, E * C)
        rep = jnp.repeat(tok, K, axis=0) if K > 1 else tok
        buf = permute_rows(rep[None], src, flat_slot, impl=impl)
        return buf.reshape(E, C, M), (flat_slot, src, rt.weight), l_aux

    def experts(buf):  # [E,C,M] -> [E,C,M], one fused GEMM pair per projection
        h = jax.nn.gelu(jnp.einsum("ecm,emf->ecf", buf, w1))
        return jnp.einsum("ecf,efm->ecm", h, w2)

    def combine_dense(combine, eo, tok):
        return jnp.einsum("sec,ecm->sm", combine.astype(tok.dtype), eo)

    def combine_sorted(meta, eo, tok):
        flat_slot, src, weight = meta
        rows = permute_rows(eo.reshape(1, E * C, M), flat_slot, src, impl=impl)
        w = weight.astype(tok.dtype).reshape(1, S * K, 1)
        return (w * rows).reshape(S, K, M).sum(axis=1)

    for route in [r.strip() for r in routes if r.strip()]:
        disp = dispatch_dense if route == "dense" else dispatch_sorted
        comb = combine_dense if route == "dense" else combine_sorted

        def p_gate(tok):
            out = (gate_dense if route == "dense" else gate_sorted)(tok)
            return out[0]  # l_aux: scalar data dep through the whole gate

        def p_dispatch(tok):
            buf, _, l_aux = disp(tok)
            return buf.astype(jnp.float32).sum() + l_aux

        def p_expert(tok):
            buf, _, l_aux = disp(tok)
            return experts(buf).astype(jnp.float32).sum() + l_aux

        def p_full(tok):
            buf, meta, l_aux = disp(tok)
            out = comb(meta, experts(buf), tok) if route == "sorted" \
                else combine_dense(meta, experts(buf), tok)
            return out.astype(jnp.float32).sum() + l_aux

        times = {}
        for tag, fn in [("gate", p_gate), ("dispatch", p_dispatch),
                        ("expert", p_expert), ("fwd", p_full),
                        ("fwd_bwd", lambda tok: jax.grad(p_full)(tok).astype(jnp.float32).sum())]:
            @jax.jit
            def prog(carry, fn=fn):
                tok, acc = carry
                v = fn(tok)
                v = v.sum() if v.ndim else v
                shift = (v * 0).astype(tok.dtype)
                return (tok + shift, acc + v.astype(jnp.float32))

            times[tag] = timed(f"moe_{route}_{tag}", lambda c: prog(c),
                               (tokens0, jnp.float32(0)))

        # routing metadata + dispatch/combine buffers materialized per route
        if route == "dense":
            meta_bytes = S * E * C * (4 + itemsize)  # combine f32 + mask cast
        else:
            meta_bytes = S * K * (4 + 4) + E * C * 4  # slots + weights + src
        peak = meta_bytes + E * C * M * itemsize     # + the [E,C,M] buffer
        print(json.dumps({
            "tag": f"moe_{route}", "moe_route": route,
            "moe_kernel": impl if route == "sorted" else None,
            "gate_ms": round(times["gate"] * 1e3, 2),
            "dispatch_ms": round((times["dispatch"] - times["gate"]) * 1e3, 2),
            "expert_ms": round((times["expert"] - times["dispatch"]) * 1e3, 2),
            "combine_ms": round((times["fwd"] - times["expert"]) * 1e3, 2),
            "fwd_ms": round(times["fwd"] * 1e3, 2),
            "fwd_bwd_ms": round(times["fwd_bwd"] * 1e3, 2),
            "dispatch_peak_bytes": int(peak),
        }), flush=True)


def pipe_schedule_ab():
    """Per-schedule pipeline A/B: measured step time + static transient
    bytes per schedule on the same mesh/model/microbatch count. CPU-safe
    (pipe-only mesh folds to full-manual shard_map on jax 0.4.37)."""
    from deepspeed_tpu.analysis import cost_engine_program
    from deepspeed_tpu.models.gpt2 import gpt2_pipe_layers
    from deepspeed_tpu.parallel.topology import MeshTopology, set_topology
    from deepspeed_tpu.runtime.pipe.module import PipelineModule

    stages = int(os.environ.get("BENCH_PIPE_STAGES", "4"))
    micros = int(os.environ.get("BENCH_PIPE_MICROS", "16"))
    mb = int(os.environ.get("BENCH_MICRO_BS", "2"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    embd = int(os.environ.get("BENCH_PIPE_EMBD", "128"))
    model = os.environ.get("BENCH_PIPE_MODEL", "test")
    schedules = os.environ.get("BENCH_PIPE_SCHEDULES", "1f1b,chunked,gpipe").split(",")
    steps = int(os.environ.get("BENCH_PIPE_STEPS", "5"))
    if len(jax.devices()) < stages:
        print(json.dumps({"tag": "pipe_ab", "error":
                          f"needs {stages} devices, have {len(jax.devices())}"}))
        return
    print(f"# pipe schedule A/B S={stages} M={micros} mb={mb} seq={seq} "
          f"embd={embd} model={model}", flush=True)
    rng = np.random.default_rng(0)
    for schedule in schedules:
        schedule = schedule.strip()
        set_topology(None)
        cfg = get_gpt2_config(model, n_layer=stages, n_embd=embd,
                              n_head=max(2, embd // 32), n_positions=seq)
        topo = MeshTopology(pipe=stages, data=1, devices=jax.devices()[:stages])
        pipe = PipelineModule(layers=gpt2_pipe_layers(cfg), topology=topo)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=pipe, topology=topo,
            config={"train_batch_size": micros * mb,
                    "gradient_accumulation_steps": micros,
                    "pipeline": {"schedule": schedule},
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                    "steps_per_print": 10**9})
        batch = {"input_ids": rng.integers(0, cfg.vocab_size,
                                           (micros * mb, seq)).astype(np.int32)}
        t0 = time.time()
        engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        compile_s = time.time() - t0
        t0 = time.time()
        for _ in range(steps):
            engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        dt = (time.time() - t0) / steps
        row = {"tag": f"pipe_{schedule}", "pipe_schedule": engine.pipe_schedule,
               "stages": stages, "micro_batches": micros,
               "chunk_microbatches": engine.pipe_chunk,
               "step_ms": round(dt * 1e3, 1),
               "compile_s": round(compile_s, 1),
               "loss": round(float(engine.train_batch(batch)), 4)}
        try:  # static evidence next to the measured number (trace-only)
            row.update(cost_engine_program(engine, batch))
        except Exception as e:  # evidence must never kill a row
            row["cost_error"] = f"{type(e).__name__}: {str(e)[:120]}"
        print(json.dumps(row), flush=True)
    set_topology(None)


def main():
    if os.environ.get("BENCH_MOE", "0") == "1":
        moe_sections()
        print("# DONE", flush=True)
        return
    if os.environ.get("BENCH_PIPE", "0") == "1":
        pipe_schedule_ab()
        print("# DONE", flush=True)
        return
    cfg = get_gpt2_config(MODEL, n_positions=SEQ, remat=True,
                          attention_backend="flash", dtype=jnp.bfloat16)
    model = GPT2LMHeadModel(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": MB,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10**9,
    })
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (MB, SEQ)).astype(np.int32)
    batch = {"input_ids": ids}
    engine.initialize_state(batch)
    params = engine.state.params
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    print(f"# breakdown {MODEL} params={n_params / 1e6:.1f}M mb={MB} seq={SEQ}",
          flush=True)
    key = jax.random.PRNGKey(0)

    def loss_fn(p, ids_dev):
        logits = model.apply({"params": p}, ids_dev, deterministic=True)
        tgt = ids_dev[:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(lp, tgt[..., None], axis=-1))

    ids_dev = jnp.asarray(ids)

    # 1) forward only — chain: perturb ids by loss-derived int so each
    # dispatch differs and depends on the previous result (params passed
    # explicitly so jit doesn't bake them in as program constants)
    @jax.jit
    def fwd(p, carry):
        ids_c, acc = carry
        l = loss_fn(p, ids_c)
        shift = (l * 0).astype(jnp.int32)  # data dep, value-neutral
        return (ids_c + shift, acc + l)

    timed("fwd", lambda c: fwd(params, c), (ids_dev, jnp.float32(0)))

    # 2) fwd + bwd (grads reduced to a scalar to keep transfer off the timing)
    @jax.jit
    def fwdbwd(p, carry):
        ids_c, acc = carry
        l, g = jax.value_and_grad(loss_fn)(p, ids_c)
        gsum = sum(jnp.sum(x.astype(jnp.float32)) for x in jax.tree.leaves(g))
        shift = (gsum * 0).astype(jnp.int32)
        return (ids_c + shift, acc + l)

    timed("fwd_bwd", lambda c: fwdbwd(params, c), (ids_dev, jnp.float32(0)))

    # 3) full engine step (state donation chains deps naturally)
    def full(carry):
        engine.train_batch(batch)
        return engine.state.params

    timed("engine_step", full, None)
    print("# DONE", flush=True)


if __name__ == "__main__":
    main()
