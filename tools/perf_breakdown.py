"""One clean-exit TPU breakdown: times fwd, fwd+bwd, and the full engine
step as separate compiled programs, each iterated with CHAINED data
dependencies (output feeds next input) so the axon tunnel's identical-
dispatch dedupe can't fake the numbers. Attribution without
jax.profiler.trace (a killed trace session wedges the tunnel).

Run: python tools/perf_breakdown.py   (background it; poll stdout —
NEVER wrap in `timeout`: a killed TPU process wedges the tunnel claim)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import jax
import jax.numpy as jnp

from bench_core import enable_compile_cache

enable_compile_cache()

import deepspeed_tpu
from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

MODEL = os.environ.get("BENCH_MODEL", "350m")
MB = int(os.environ.get("BENCH_MICRO_BS", "4"))
SEQ = int(os.environ.get("BENCH_SEQ", "1024"))
N = 10


def timed(tag, fn, carry):
    """fn: carry -> carry with chained deps. Times N iterations."""
    carry = fn(carry)  # warmup (compile)
    jax.block_until_ready(carry)
    t0 = time.time()
    for _ in range(N):
        carry = fn(carry)
    jax.block_until_ready(carry)
    dt = (time.time() - t0) / N
    print(json.dumps({"tag": tag, "ms": round(dt * 1e3, 1)}), flush=True)
    return dt


def main():
    cfg = get_gpt2_config(MODEL, n_positions=SEQ, remat=True,
                          attention_backend="flash", dtype=jnp.bfloat16)
    model = GPT2LMHeadModel(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": MB,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10**9,
    })
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (MB, SEQ)).astype(np.int32)
    batch = {"input_ids": ids}
    engine.initialize_state(batch)
    params = engine.state.params
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    print(f"# breakdown {MODEL} params={n_params / 1e6:.1f}M mb={MB} seq={SEQ}",
          flush=True)
    key = jax.random.PRNGKey(0)

    def loss_fn(p, ids_dev):
        logits = model.apply({"params": p}, ids_dev, deterministic=True)
        tgt = ids_dev[:, 1:]
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(lp, tgt[..., None], axis=-1))

    ids_dev = jnp.asarray(ids)

    # 1) forward only — chain: perturb ids by loss-derived int so each
    # dispatch differs and depends on the previous result (params passed
    # explicitly so jit doesn't bake them in as program constants)
    @jax.jit
    def fwd(p, carry):
        ids_c, acc = carry
        l = loss_fn(p, ids_c)
        shift = (l * 0).astype(jnp.int32)  # data dep, value-neutral
        return (ids_c + shift, acc + l)

    timed("fwd", lambda c: fwd(params, c), (ids_dev, jnp.float32(0)))

    # 2) fwd + bwd (grads reduced to a scalar to keep transfer off the timing)
    @jax.jit
    def fwdbwd(p, carry):
        ids_c, acc = carry
        l, g = jax.value_and_grad(loss_fn)(p, ids_c)
        gsum = sum(jnp.sum(x.astype(jnp.float32)) for x in jax.tree.leaves(g))
        shift = (gsum * 0).astype(jnp.int32)
        return (ids_c + shift, acc + l)

    timed("fwd_bwd", lambda c: fwdbwd(params, c), (ids_dev, jnp.float32(0)))

    # 3) full engine step (state donation chains deps naturally)
    def full(carry):
        engine.train_batch(batch)
        return engine.state.params

    timed("engine_step", full, None)
    print("# DONE", flush=True)


if __name__ == "__main__":
    main()
