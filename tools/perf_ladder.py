"""Bench the config ladder's larger rungs on the real chip, one clean-exit
process. NEVER wrap this in `timeout` and never kill it — a killed TPU
process wedges the axon tunnel claim (PERF.md wedge #3: a 760m fused-10
compile alone can exceed 25 min). The script instead checks an INTERNAL
deadline between rungs and exits cleanly; a rung whose compile is in
flight is allowed to finish. Each rung is try/except-isolated; results
print as they land. Measurement methodology is shared with the other
perf tools via bench_core.

Run: python tools/perf_ladder.py            (background it; poll stdout)
Env: LADDER=760m_mb4,760m_mb8,xl_offload_mb1  (comma list; default 760m)
     LADDER_DEADLINE=3600  (seconds; checked between rungs only)
     LADDER_FUSED=10       (steps per fused dispatch; lower = faster compile)
     LADDER_RETRIES=3      (attempts per rung on transient tunnel failures —
                            the remote-compile-helper HTTP 500 class; backoff
                            base LADDER_RETRY_BASE=15s, heartbeat-aware)
     LADDER_TELEMETRY=1    (graft-trace evidence: per-phase span medians +
                            drift ratios on every rung row; 0 opts out.
                            JSONLs land under LADDER_TELEMETRY_DIR, default
                            /tmp/ds_tpu_ladder_telemetry/<tag>)

Transient-failure policy (resilience/retry.py): a rung that dies with a
compile-helper 500 / connection flake is retried with backoff+jitter; the
attempt history rides the rung's evidence row (``retries`` +
``retry_history``) so banked numbers show what they survived. A rung whose
retries exhaust emits a STRUCTURED row — ``blocked: compile_helper_500``
with the full history — instead of a bare error (PERF.md §PR9 envelope).
"""
import json
import os
import sys
import time
import traceback

# multi-device CPU smoke (pipe rungs need a pipe mesh): LADDER_DEVICES=N
# forces a virtual host-device count, same contract as GRAFT_LINT_DEVICES.
# Must land in XLA_FLAGS before bench_core imports jax.
_n_dev = os.environ.get("LADDER_DEVICES")
if _n_dev and "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + f" --xla_force_host_platform_device_count={_n_dev}").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_core import (build_engine, enable_compile_cache, report,
                        time_fused, time_per_dispatch)

SEQ = 1024


def run_rung(tag, model_name, mb, offload=False, steps=None, seq=None,
             fused_xent=False, ds=None, cfg_overrides=None, pipe_stages=0,
             retry_evidence=None, retry_evidence_extra=None):
    ds_overrides = dict(ds or {})
    if offload:
        # full ZeRO-Infinity single-chip recipe: params rest pinned-host and
        # stream through the step (offload_param), masters + moments on the
        # host C++ Adam (offload_optimizer) — runtime/zero/param_offload.py
        ds_overrides["zero_optimization"] = {
            "stage": 3,
            "offload_param": {"device": "cpu", "pin_memory": True},
            "offload_optimizer": {"device": "cpu", "pin_memory": True},
        }
    if model_name == "bert_test":  # smoke rung: keep the tiny test vocab
        overrides = {}
    elif model_name.startswith("bert_"):
        # lane-aligned vocab (30522 → 30592, x128); BERT has no causal LM
        # head so the GPT-2 fused-xent/onehot knobs don't apply
        overrides = {"vocab_size": 30592}
    elif model_name == "test":  # smoke rungs: keep the tiny 256 vocab
        overrides = {}
    else:
        overrides = {"vocab_size": 50304, "embed_onehot_grad": True}
        if fused_xent:
            overrides["fused_head_loss_chunk"] = 1024
    overrides.update(cfg_overrides or {})  # rung-specific model-config knobs (MoE, ...)
    if os.environ.get("LADDER_TELEMETRY", "1") == "1":
        # graft-trace evidence: span timeline + drift ratios for the rung's
        # own steps (run header carries the static price). ≤2% overhead by
        # the tier-1 gate; LADDER_TELEMETRY=0 opts out for A/B paranoia.
        ds_overrides.setdefault("telemetry", {
            "enabled": True,
            "output_path": os.environ.get("LADDER_TELEMETRY_DIR",
                                          "/tmp/ds_tpu_ladder_telemetry"),
            "job_name": tag})
    engine, batch, n_params, cfg = build_engine(
        model_name, mb, seq or SEQ, ds_overrides=ds_overrides,
        pipe_stages=pipe_stages, **overrides)
    if offload:
        # host-driven schedule: per-step dispatch is the real path here
        n_steps, dt, compile_s = time_per_dispatch(engine, batch, steps or 3)
    else:
        fused = int(os.environ.get("LADDER_FUSED", "10"))
        n_steps, dt, compile_s = time_fused(engine, batch, fused=fused)
    # ONE trace shared by both static-evidence paths (tracing a real
    # model's step costs seconds; lint and cost must not each pay it)
    programs = _traced_programs_evidence(engine, batch)
    report(tag, mb, seq or SEQ, n_params, n_steps, dt, compile_s, cfg=cfg,
           **attn_geometry_evidence(cfg, mb, seq or SEQ),
           **moe_route_evidence(cfg),
           **lint_evidence(engine, batch, programs),
           **cost_evidence(engine, batch, programs),
           **telemetry_evidence(engine),
           **calibration_evidence(programs),
           **(retry_evidence_extra or {}),
           **(retry_evidence or {}))


def _traced_programs_evidence(engine, batch):
    """The engine's traced step, computed once for every evidence helper
    that needs it; None (with the evidence paths degrading to their own
    error rows) when tracing itself fails or both paths are opted out."""
    if (os.environ.get("LADDER_LINT", "1") != "1"
            and os.environ.get("LADDER_COST", "1") != "1"):
        return None
    try:
        return engine.traced_programs(batch)
    except Exception:  # each evidence helper reports its own error row
        return None


def attn_geometry_evidence(cfg, mb, seq):
    """Which flash-attention geometry this rung ran, and which resolution
    layer picked it (explicit/env/config/cache/default) — rows regenerate
    the PERF.md long-context table, so the chosen partitioning must ride
    next to the TFLOPS it produced."""
    if getattr(cfg, "attention_backend", None) != "flash":
        return {}
    try:
        import jax.numpy as jnp

        from deepspeed_tpu.ops.pallas.attention_geometry import (parse_spec,
                                                                 resolve_geometry)
        heads = getattr(cfg, "n_head", None) or getattr(cfg, "num_attention_heads", 1)
        causal = hasattr(cfg, "n_layer") or hasattr(cfg, "rope_theta")
        # mirror the kernel's resolution exactly: a per-model
        # attention_blocks pin is the highest-precedence (clamped) layer
        spec = getattr(cfg, "attention_blocks", None)
        geom, src = resolve_geometry(seq, seq, cfg.head_dim, heads, mb, causal,
                                     jnp.dtype(cfg.dtype),
                                     overrides=parse_spec(spec) if spec else None)
        return {"attn_geometry": geom.spec(), "attn_geometry_source": src}
    except Exception as e:  # evidence must never kill a rung
        return {"attn_geometry": f"error: {type(e).__name__}: {str(e)[:120]}",
                "attn_geometry_source": "error"}


def moe_route_evidence(cfg):
    """Which MoE dispatch/combine route this rung ran and which resolution
    layer picked it (explicit/env/config/default) — the dense-vs-sorted A/B
    rows regenerate PERF.md's MoE table, so the route must ride next to the
    TFLOPS it produced (same contract as attn_geometry_source)."""
    if not getattr(cfg, "moe_num_experts", 0):
        return {}
    try:
        from deepspeed_tpu.moe.routing import resolve_route
        route, kernel, src = resolve_route(getattr(cfg, "moe_route", None))
        return {"moe_route": route, "moe_route_source": src,
                "moe_kernel": kernel if route == "sorted" else None}
    except Exception as e:  # evidence must never kill a rung
        return {"moe_route": f"error: {type(e).__name__}: {str(e)[:120]}",
                "moe_route_source": "error"}


def telemetry_evidence(engine):
    """graft-trace evidence for the rung: per-phase span medians (ms) and
    predicted-vs-measured drift ratios from the rung's OWN measured steps
    (runtime/telemetry drift_summary — achieved TFLOPS from flops_proxy ÷
    median step time, memory-peak ratios where the backend reports them).
    A banked TFLOPS row thereby carries its cost-model error next to the
    lint/cost evidence. Evidence must never kill a rung; LADDER_TELEMETRY=0
    opts the whole subsystem out (the engine then runs telemetry-off)."""
    if os.environ.get("LADDER_TELEMETRY", "1") != "1":
        return {}
    try:
        tel = getattr(engine, "telemetry", None)
        if tel is None or not tel.enabled:
            return {}
        return {"telemetry": tel.drift_summary()}
    except Exception as e:  # evidence must never kill a rung
        return {"telemetry_error": f"{type(e).__name__}: {str(e)[:120]}"}


def lint_evidence(engine, batch, programs=None):
    """graft-lint summary of the step program this rung actually measured
    (rule hit counts / waivers / clean flag — deepspeed_tpu/analysis): a
    banked TFLOPS row must prove the measured program passed the same
    static gates CI enforces, or a window could bank a number from a
    program the next commit is forbidden to reproduce. Trace-only, a few
    seconds against the rung's compile minutes; LADDER_LINT=0 opts out."""
    if os.environ.get("LADDER_LINT", "1") != "1":
        return {}
    try:
        from deepspeed_tpu.analysis import lint_engine_program
        return lint_engine_program(engine, batch, programs=programs)
    except Exception as e:  # evidence must never kill a rung
        return {"lint_error": f"{type(e).__name__}: {str(e)[:120]}"}


def cost_evidence(engine, batch, programs=None):
    """graft-audit static-cost summary of the measured step program
    (deepspeed_tpu/analysis/cost.py): predicted peak bytes (total +
    transient) and analytic wire bytes per inventory layer, so every
    banked TFLOPS number carries its predicted memory/comms cost next to
    the measured one — the window-to-window sanity check that a faster
    rung didn't buy its speed with a fatter schedule. Trace-only (the
    rung's own compile is never repeated for evidence); the compiled
    collective layer therefore appears only where the trace carries
    explicit collectives (shard_map programs) or reshard sites.
    LADDER_COST=0 opts out."""
    if os.environ.get("LADDER_COST", "1") != "1":
        return {}
    try:
        from deepspeed_tpu.analysis import cost_engine_program
        return cost_engine_program(engine, batch, programs=programs)
    except Exception as e:  # evidence must never kill a rung
        return {"cost_error": f"{type(e).__name__}: {str(e)[:120]}"}


def calibration_evidence(programs):
    """graft-calibrate evidence: the rung's step program priced in
    predicted wall SECONDS under the committed measured-mode calibration
    (analysis_results/cost_calibration.json), stamped next to the
    measured ms — every banked row thereby carries the calibrated
    model's claim so the drift between them is auditable per window
    (rule R016 gates the artifact itself). Silently absent when no
    calibration is banked or the entry can't price this program;
    evidence must never kill a rung."""
    if programs is None or os.environ.get("LADDER_COST", "1") != "1":
        return {}
    try:
        from deepspeed_tpu.analysis import (calibrated_seconds,
                                            calibration_entry,
                                            load_calibration,
                                            static_price_from_programs)
        entry, key = calibration_entry(load_calibration())
        if entry is None:
            return {}
        sec = calibrated_seconds(static_price_from_programs(programs),
                                 entry["coeffs"])
        if sec is None:
            return {}
        return {"predicted_step_s_calibrated": sec, "calibration_key": key}
    except Exception as e:  # evidence must never kill a rung
        return {"calibration_error": f"{type(e).__name__}: {str(e)[:120]}"}


RUNGS = {
    # harness smoke rungs (tiny model): validate the fused and offload
    # measurement paths in seconds on any backend before burning a chip
    # window on the real rungs
    "smoke": dict(model_name="test", mb=2, seq=64),
    "smoke_offload": dict(model_name="test", mb=2, seq=64, offload=True, steps=2),
    "smoke_bert": dict(model_name="bert_test", mb=2, seq=64),
    "smoke_moe": dict(model_name="test", mb=2, seq=64,
                      cfg_overrides=dict(moe_num_experts=2, moe_layer_freq=2,
                                         moe_k=1)),
    "760m_mb4": dict(model_name="760m", mb=4),
    "760m_mb8": dict(model_name="760m", mb=8),
    # plain 760m_mb8 OOMs by 2.6G; the chunked fused head removes the
    # [B,L,V] logits + cotangent buffers (~2x0.77G bf16 + f32 temps)
    "760m_mb8_fx": dict(model_name="760m", mb=8, fused_xent=True),
    "760m_mb4_fx": dict(model_name="760m", mb=4, fused_xent=True),
    # offload A/B at the bench operating point: quantifies the ZeRO-Infinity
    # streaming overhead against the dense 70-TFLOPS configuration
    "350m_offload_mb8": dict(model_name="350m", mb=8, offload=True, steps=3,
                             fused_xent=True),
    "xl_offload_mb1": dict(model_name="xl", mb=1, offload=True, steps=2),
    "xl_offload_mb4": dict(model_name="xl", mb=4, offload=True, steps=2),
    # single-chip GPT-MoE rung + its dense base A/B (measured r5 on chip:
    # 2.6x params at 1.30x step cost; larger MoE geometries OOM one chip
    # dense — EP weak-scaling evidence covers those). TFLOPS uses active
    # params (flops_per_token_from_cfg MoE accounting).
    "125m_mb8": dict(model_name="125m", mb=8, fused_xent=True),
    "125m_moe8_mb8": dict(model_name="125m", mb=8, fused_xent=True,
                          cfg_overrides=dict(moe_num_experts=8,
                                             moe_layer_freq=2, moe_k=1)),
    # dispatch-route A/B at the same operating point: 125m_moe8_mb8 runs
    # the resolved default (sorted unless overridden); this rung pins the
    # dense einsum route so the sorted-route gain is measured in one window
    # (ROADMAP 3c: >=58 active-TFLOPS target, from 48.8 dense)
    "125m_moe8_mb8_dense": dict(model_name="125m", mb=8, fused_xent=True,
                                cfg_overrides=dict(moe_num_experts=8,
                                                   moe_layer_freq=2, moe_k=1,
                                                   moe_route="dense")),
    # pipeline-schedule A/B at the 350m judged config (PR 11): same mesh,
    # same 16-microbatch global batch, only the tick schedule differs.
    # 1f1b holds the constant 2(S-1)-slot activation stash with per-tick
    # fwd/bwd interleave; chunked pays a fill/drain bubble per C=4 wave
    # and ~2x the activation bound (CPU A/B: 1f1b 1.19x faster at the
    # M=16/S=4 test shape, PERF.md §PR11 — the chip window prices the
    # same pair at real scale, where the freed HBM also buys microbatch)
    "350m_pipe4_1f1b": dict(model_name="350m", mb=16, pipe_stages=4,
                            ds={"gradient_accumulation_steps": 16,
                                "pipeline": {"schedule": "1f1b"}}),
    "350m_pipe4_chunked": dict(model_name="350m", mb=16, pipe_stages=4,
                               ds={"gradient_accumulation_steps": 16,
                                   "pipeline": {"schedule": "chunked",
                                                "chunk_microbatches": 4}}),
    "smoke_pipe": dict(model_name="test", mb=8, seq=64, pipe_stages=2,
                       ds={"gradient_accumulation_steps": 4,
                           "pipeline": {"schedule": "1f1b"}}),
    # long-context rungs: the gridded flash kernel streams K/V blocks, so
    # VMEM no longer caps sequence length; fused xent keeps the logits
    # buffers off the OOM line at long L. Rows report the chosen attention
    # block geometry + its source — run tools/attn_tune.py first to bank
    # shape-keyed winners, or force one via DS_ATTN_BLOCKS.
    "350m_seq2k": dict(model_name="350m", mb=4, seq=2048, fused_xent=True),
    "350m_seq4k": dict(model_name="350m", mb=2, seq=4096, fused_xent=True),
    "350m_seq8k": dict(model_name="350m", mb=1, seq=8192, fused_xent=True),
    # compile-helper-500 bisect rungs (PERF.md §PR9): straddle each model
    # family's known-good/known-bad boundary. Run at the next window as one
    # stage; with LADDER_RETRIES active, each row's retry_history says
    # whether the 500 is deterministic at that size or a helper-restart
    # flake — the envelope falls out of one LADDER=bisect_* invocation.
    "bisect_bert_mb160": dict(model_name="bert_large", mb=160, seq=128),
    "bisect_bert_mb192": dict(model_name="bert_large", mb=192, seq=128),
    "bisect_bert_mb224": dict(model_name="bert_large", mb=224, seq=128),
    "bisect_350m_mb10": dict(model_name="350m", mb=10, fused_xent=True),
    "bisect_350m_mb12": dict(model_name="350m", mb=12, fused_xent=True),
    "bisect_760m_mb5": dict(model_name="760m", mb=5, fused_xent=True),
    "bisect_760m_mb6": dict(model_name="760m", mb=6, fused_xent=True),
    # the reference's 64-TFLOPS headline workload: BERT-large pretrain at
    # seq 128 (BASELINE.md row 1) — direct apples-to-apples rung
    "bert_large_mb64": dict(model_name="bert_large", mb=64, seq=128),
    "bert_large_mb128": dict(model_name="bert_large", mb=128, seq=128),
    "bert_large_mb256": dict(model_name="bert_large", mb=256, seq=128),
    # BERT-large ZeRO-1 + FusedAdam is the ladder's second judged config
    # ("Adam" = the optax XLA-fused Adam, this repo's FusedAdam role; on
    # one chip ZeRO-1's shards are trivially whole but the config path is
    # the judged one)
    "bert_large_seq512_mb32": dict(model_name="bert_large", mb=32, seq=512,
                                   ds={"zero_optimization": {"stage": 1},
                                       "optimizer": {"type": "Adam",
                                                     "params": {"lr": 1e-4}}}),
}


#: graft-serve latency-under-load rungs (ISSUE 14): each is a committed
#: tools/serve_bench.py configuration, so the next chip window measures
#: the serving curve for free. Rows carry the bench's own evidence
#: columns — serve_lint / serve_cost_* (graft-audit price of the decode
#: program actually served) and, via SERVE_TELEMETRY, per-tick span
#: medians + drift — next to goodput and p50/p99 TTFT / per-token
#: latency. The continuous-vs-static comparison row rides the b32 rung;
#: chunked-prefill and speculation are isolated A/Bs on one knob each.
SERVE_RUNGS = {
    # the measured decode sweet spot (PERF.md decode sweep: batch 32):
    # continuous vs static at equal offered load, the headline comparison
    "serve_qps_b32": {"SERVE_MODE": "both", "SERVE_SLOTS": "32",
                      "SERVE_QPS": "16", "SERVE_REQUESTS": "96",
                      "SERVE_PROMPT": "64", "SERVE_NEW": "32"},
    # chunked prefill A/B: every 4th prompt is 4x long; CHUNK=0 disables
    # chunking (whole-prompt prefill ticks stall in-flight decodes)
    "serve_qps_chunked_on": {"SERVE_MODE": "continuous", "SERVE_SLOTS": "8",
                             "SERVE_QPS": "8", "SERVE_REQUESTS": "48",
                             "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                             "SERVE_LONG_EVERY": "4", "SERVE_CHUNK": "16"},
    "serve_qps_chunked_off": {"SERVE_MODE": "continuous", "SERVE_SLOTS": "8",
                              "SERVE_QPS": "8", "SERVE_REQUESTS": "48",
                              "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                              "SERVE_LONG_EVERY": "4", "SERVE_CHUNK": "0"},
    # speculation A/B: KD-student drafter on/off at the same trace
    "serve_qps_spec_on": {"SERVE_MODE": "continuous", "SERVE_SLOTS": "8",
                          "SERVE_QPS": "8", "SERVE_REQUESTS": "48",
                          "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                          "SERVE_SPEC": "1", "SERVE_SPEC_K": "4"},
    "serve_qps_spec_off": {"SERVE_MODE": "continuous", "SERVE_SLOTS": "8",
                           "SERVE_QPS": "8", "SERVE_REQUESTS": "48",
                           "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                           "SERVE_SPEC": "0"},
    # graft-quant-serve A/B (ISSUE 16): fp vs int8/int4 weights + int8 KV
    # on the same trace under the SAME KV byte budget (unset POOL_BYTES =
    # half the fp full-context footprint, so fp is admission-starved at
    # saturation while quant holds every slot). Rows carry blocks-per-GB
    # and the comparison row carries goodput ratio + token-level greedy
    # match of the quantized arm vs fp (PERF.md §PR16).
    "serve_qps_wq8": {"SERVE_MODE": "quant_ab", "SERVE_SLOTS": "8",
                      "SERVE_QPS": "16", "SERVE_REQUESTS": "48",
                      "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                      "SERVE_WQ": "int8"},
    "serve_qps_wq4": {"SERVE_MODE": "quant_ab", "SERVE_SLOTS": "8",
                      "SERVE_QPS": "16", "SERVE_REQUESTS": "48",
                      "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                      "SERVE_WQ": "int4"},
    # graft-prefix-cache rungs (ISSUE 19): the seeded shared-prefix trace
    # (8 templates, each 3/4 of the prompt) served cache-on vs cache-off
    # at IDENTICAL pool bytes. The comparison row carries goodput ratio,
    # per-arm TTFT p99, hit rate / cached-blocks evidence, and the
    # token-level greedy match — which must be EXACT (a restored block is
    # the same KV bytes prefill would have written). QPS saturates the
    # 8 slots so prefill compute is the contended resource the cache
    # relieves (PERF.md §PR19). Three geometry choices are load-bearing
    # and each was MEASURED to flip the A/B when wrong:
    #  - POOL_TOKENS sizes the pool ABOVE slots x context (the
    #    default): 192 blocks = 104 in-use at saturation + 72 for the
    #    8 shared templates + headroom. At the default the spare
    #    capacity can't hold one 9-block template and the LRU thrashes
    #    (measured: hit rate 0.83 -> 0.48, 263 evictions, cache-on
    #    LOSES 0.76x). A prefix cache needs the deployment reality of
    #    spare pool; both arms price the same bytes either way.
    #  - NEW_JITTER: with every request decoding exactly NEW tokens,
    #    slots free in perfect waves of 8 and the OFF arm prefills in
    #    fully-batched cohorts — an artifact of uniform lengths that
    #    mixed hot/cold admission then breaks (measured: cache-on
    #    0.93x despite hit rate 0.75, prefill ticks UP 42 -> 48 on
    #    HALF the slot-chunks). Variable output lengths fragment both
    #    arms alike and let the 2x work cut show up as ticks.
    #  - NEW=16 << PROMPT=192 is the workload prefix caching exists
    #    for (RAG / few-shot: long shared prompt, short completion);
    #    at NEW=32 decode ticks dominate the budget and cap the best
    #    possible ratio near 1.1x.
    "serve_prefix_ab": {"SERVE_MODE": "prefix_ab", "SERVE_SLOTS": "8",
                        "SERVE_QPS": "16", "SERVE_REQUESTS": "48",
                        "SERVE_PROMPT": "192", "SERVE_NEW": "16",
                        "SERVE_NEW_JITTER": "1",
                        "SERVE_CHUNK": "32", "SERVE_SHARED_PREFIX": "8",
                        "SERVE_POOL_TOKENS": "3072"},
    # prefix-affinity fleet routing: the same shared-prefix trace through
    # 2 replicas, affinity dispatch (replicas advertise their hot root
    # prefixes in tick signals) vs pure least-loaded (FLEET_AFFINITY=0).
    # Affinity keeps same-template requests on the replica already
    # holding their prefix blocks — the control arm scatters each
    # template across both replicas, paying ~2x the fleet-wide cold
    # prefills and duplicating every template's blocks in both pools
    # (per-worker hit rate / cold counts in the replica telemetry are
    # the evidence; on a 1-core rig the goodput delta is muted because
    # the replicas' compute serializes either way).
    "serve_prefix_fleet_affinity": {
        "SERVE_MODE": "fleet", "SERVE_REPLICAS": "2", "SERVE_QPS": "16",
        "SERVE_REQUESTS": "48", "SERVE_PROMPT": "192", "SERVE_NEW": "16",
        "SERVE_NEW_JITTER": "1",
        "SERVE_SLOTS": "8", "SERVE_CHUNK": "32", "SERVE_SHARED_PREFIX": "8",
        "SERVE_POOL_TOKENS": "3072"},
    "serve_prefix_fleet_leastloaded": {
        "SERVE_MODE": "fleet", "SERVE_REPLICAS": "2", "SERVE_QPS": "16",
        "SERVE_REQUESTS": "48", "SERVE_PROMPT": "192", "SERVE_NEW": "16",
        "SERVE_NEW_JITTER": "1",
        "SERVE_SLOTS": "8", "SERVE_CHUNK": "32", "SERVE_SHARED_PREFIX": "8",
        "SERVE_POOL_TOKENS": "3072", "FLEET_AFFINITY": "0"},
    # graft-fleet scaling rungs (ISSUE 17): the SAME trace through a
    # FleetRouter over N real worker subprocesses (fleet/worker.py; each
    # builds + warms its own engine off the clock). The x1/x2/x4 trio
    # regenerates the PERF.md §PR17 goodput-scaling row at pinned TTFT
    # p99; the smoke rung proves the subprocess plumbing in seconds on
    # any backend before a window pays for the real trio.
    "serve_fleet_smoke": {"SERVE_MODE": "fleet", "SERVE_MODEL": "test",
                          "SERVE_REPLICAS": "2", "SERVE_QPS": "16",
                          "SERVE_REQUESTS": "12", "SERVE_PROMPT": "16",
                          "SERVE_NEW": "8", "SERVE_SLOTS": "4",
                          "SERVE_CHUNK": "8"},
    "serve_fleet_x1": {"SERVE_MODE": "fleet", "SERVE_REPLICAS": "1",
                       "SERVE_QPS": "16", "SERVE_REQUESTS": "64",
                       "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                       "SERVE_SLOTS": "8"},
    "serve_fleet_x2": {"SERVE_MODE": "fleet", "SERVE_REPLICAS": "2",
                       "SERVE_QPS": "16", "SERVE_REQUESTS": "64",
                       "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                       "SERVE_SLOTS": "8"},
    "serve_fleet_x4": {"SERVE_MODE": "fleet", "SERVE_REPLICAS": "4",
                       "SERVE_QPS": "16", "SERVE_REQUESTS": "64",
                       "SERVE_PROMPT": "64", "SERVE_NEW": "32",
                       "SERVE_SLOTS": "8"},
}


def run_serve_rung(tag, serve_env, retry_evidence=None):
    """One serving rung: tools/serve_bench.py in a clean subprocess (its
    own engine + scheduler state; a wedged serve can't poison later
    rungs), each of its JSON rows re-emitted with the rung tag and any
    retry evidence. Never wrapped in `timeout` (serve_bench contract)."""
    import subprocess
    env = dict(os.environ)
    env.setdefault("SERVE_MODEL", "350m")
    env.setdefault("SERVE_TELEMETRY", "1")
    env.update(serve_env)
    p = subprocess.run([sys.executable,
                        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                     "serve_bench.py")],
                       env=env, capture_output=True, text=True)
    emitted = 0
    for line in p.stdout.splitlines():
        if line.startswith("{"):
            row = json.loads(line)
            print(json.dumps(dict({"tag": tag}, **row,
                                  **(retry_evidence or {}))), flush=True)
            emitted += 1
        elif line.startswith("#"):
            print(line, flush=True)
    if p.returncode != 0 or not emitted:
        raise RuntimeError(f"serve rung {tag} failed rc={p.returncode}: "
                           f"{p.stderr[-400:]}")


#: graft-rlhf rungs (ISSUE 20): tools/rlhf_bench.py on the SAME indexed
#: prompt trace + per-rollout budget mix, in-flight loop vs serial
#: generate-then-train. ``rlhf_overlap_on`` emits the A/B pair + ratio
#: row in one process (both arms must bank identical experience tokens —
#: the bench asserts it); ``rlhf_overlap_off`` re-measures the serial arm
#: alone so a window can re-baseline without paying the loop. Rows carry
#: the planner-priced weight-sync evidence (gather_bytes per sync,
#: digest_verified) and the run dirs stamp rlhf_rollout / rlhf_learner
#: calibration headers (the rlhf_overlap marker collect_samples keys on).
RLHF_RUNGS = {
    "rlhf_overlap_on": {"RLHF_MODE": "ab", "RLHF_BATCH": "8",
                        "RLHF_PROMPT": "64", "RLHF_NEW": "64",
                        "RLHF_ROLLOUTS": "32", "RLHF_SLOTS": "8",
                        "RLHF_SYNC_EVERY": "1"},
    "rlhf_overlap_off": {"RLHF_MODE": "off", "RLHF_BATCH": "8",
                         "RLHF_PROMPT": "64", "RLHF_NEW": "64",
                         "RLHF_ROLLOUTS": "32", "RLHF_SLOTS": "8",
                         "RLHF_SYNC_EVERY": "1"},
}


def run_rlhf_rung(tag, rlhf_env, retry_evidence=None):
    """One graft-rlhf rung: tools/rlhf_bench.py in a clean subprocess
    (its own hybrid engine + scheduler; same isolation contract as the
    serve rungs), each JSON row re-emitted with the rung tag and retry
    evidence. Never wrapped in `timeout` (bench contract)."""
    import subprocess
    import tempfile
    env = dict(os.environ)
    env.setdefault("RLHF_MODEL", "350m")
    env.setdefault("RLHF_TELEMETRY",
                   tempfile.mkdtemp(prefix=f"rlhf_ladder_{tag}_"))
    env.update(rlhf_env)
    p = subprocess.run([sys.executable,
                        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                     "rlhf_bench.py")],
                       env=env, capture_output=True, text=True)
    emitted = 0
    for line in p.stdout.splitlines():
        if line.startswith("{"):
            row = json.loads(line)
            print(json.dumps(dict({"tag": tag}, **row,
                                  telemetry_dir=env["RLHF_TELEMETRY"],
                                  **(retry_evidence or {}))), flush=True)
            emitted += 1
        elif line.startswith("#"):
            print(line, flush=True)
    if p.returncode != 0 or not emitted:
        raise RuntimeError(f"rlhf rung {tag} failed rc={p.returncode}: "
                           f"{p.stderr[-400:]}")


def _frontier_rungs():
    """Rungs generated FROM the committed graft-search Pareto frontier
    (analysis_results/search_pareto.json, 350m_judged space): the next
    chip window measures exactly the statically-surviving candidate set —
    never a dominated loser (ISSUE 12 / ROADMAP 3). Pareto-tied
    candidates (identical static metrics, e.g. fused-vs-split QKV, which
    the static model cannot distinguish — only the chip can) collapse to
    their first enumerated representative so the window pays one rung per
    distinct static price point; the skipped ties are listed in the
    rung's ``search_ties`` evidence. The remat/chunk/fusion knobs route
    through the engine "program" block + optimizer.legacy_fusion exactly
    as priced; attention is the ONE deliberate delta — the frontier was
    priced on the backend-reproducible XLA attention program while the
    rung measures under the bench methodology's flash kernel, so each
    rung stamps ``search_priced_backend: "xla"`` next to its candidate id
    (the priced no-remat transients are dominated by XLA's materialized
    scores; flash removes that term, which only WIDENS the frontier's
    remat/chunk wins — the window verifies, it does not assume)."""
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "analysis_results", "search_pareto.json")
    if not os.path.exists(path):
        return {}
    # the validated loader, not raw json: a version-bumped or corrupt
    # artifact must refuse loudly here exactly as it does in graft_lint
    from deepspeed_tpu.analysis.search import load_search_artifact
    space = load_search_artifact(path).get("spaces", {}).get("350m_judged")
    if not space:
        return {}
    # calibrated artifacts carry seconds_rank — the frontier re-ranked in
    # predicted wall seconds under the committed cost calibration — so
    # the window measures winners in the order the measured-mode model
    # expects them to finish; uncalibrated artifacts keep proxy order
    order = space.get("seconds_rank") or space["frontier"]
    rungs, seen_metrics = {}, {}
    for cid in order:
        entry = space["candidates"][cid]
        knobs, metrics = entry["knobs"], entry["metrics"]
        key = tuple(metrics.get(o) for o in space["objectives"])
        if key in seen_metrics:
            rungs[seen_metrics[key]].setdefault("retry_evidence_extra", {}) \
                .setdefault("search_ties", []).append(cid)
            continue
        from deepspeed_tpu.analysis.search import Candidate
        ds = {"program": Candidate(**knobs).program_block()}
        if knobs.get("optimizer") == "chained":
            ds["optimizer"] = {"type": "AdamW", "legacy_fusion": True,
                               "params": {"lr": 1e-4, "weight_decay": 0.01}}
        slug = (knobs["remat"].replace(":", "-").replace("_", "") +
                f"_h{knobs['lm_head_chunk']}"
                + ("" if knobs.get("fused_qkv", True) else "_qkvsplit")
                + ("" if knobs.get("fused_attn_out", True) else "_outreshape")
                + ("" if knobs.get("optimizer", "fused") == "fused" else "_optchained"))
        tag = f"350m_search_{slug}"
        seen_metrics[key] = tag
        evidence = {"search_candidate": cid,
                    "search_space": "350m_judged",
                    "search_priced_backend": "xla"}
        if space.get("seconds_rank"):
            evidence["search_predicted_seconds"] = metrics.get("predicted_seconds")
            evidence["search_seconds_rank"] = order.index(cid) + 1
            evidence["search_proxy_rank"] = space["frontier"].index(cid) + 1
        rungs[tag] = dict(
            model_name="350m", mb=space["model"]["micro_bs"],
            seq=space["model"]["seq"], ds=ds,
            retry_evidence_extra=evidence)
    return rungs


def _install_frontier_rungs():
    try:
        for tag, spec in _frontier_rungs().items():
            RUNGS.setdefault(tag, spec)
    except Exception as e:  # a corrupt artifact must not kill the ladder
        print(f"# frontier rungs unavailable: {type(e).__name__}: {e}",
              file=sys.stderr)


_install_frontier_rungs()


def _rung_retry_policy():
    from deepspeed_tpu.runtime.resilience.retry import RetryPolicy, heartbeat_sleep
    return RetryPolicy(max_attempts=int(os.environ.get("LADDER_RETRIES", "3")),
                       base_delay=float(os.environ.get("LADDER_RETRY_BASE", "15")),
                       max_delay=300.0, jitter=0.25,
                       # backoff naps keep the agent's heartbeat fresh: a rung
                       # waiting out a helper restart must not read as hung
                       sleep=heartbeat_sleep())


def main():
    enable_compile_cache()
    from deepspeed_tpu.runtime.resilience.retry import classify_failure
    deadline = time.time() + int(os.environ.get("LADDER_DEADLINE", "3600"))
    want = os.environ.get("LADDER", "760m_mb4,760m_mb8").split(",")
    print(f"# ladder seq={SEQ}: {want}", flush=True)
    for tag in want:
        if time.time() > deadline:
            print(f"# deadline reached, skipping {tag} onward", flush=True)
            break
        policy = _rung_retry_policy()
        evidence = {}  # mutated before each attempt; report() reads it live

        def attempt(i, history, _ev=evidence, _tag=tag):
            from deepspeed_tpu.elasticity import touch_heartbeat
            touch_heartbeat()  # supervised runs: fresh clock before each attempt
            _ev.clear()
            _ev.update(policy.evidence())
            if i > 1:
                print(f"# {_tag}: retry attempt {i}/{policy.max_attempts} after "
                      f"{history[-1]['error_class'] or 'transient failure'}", flush=True)

        try:
            if tag.strip() in SERVE_RUNGS:
                policy.call(run_serve_rung, tag, SERVE_RUNGS[tag.strip()],
                            retry_evidence=evidence, before_attempt=attempt)
            elif tag.strip() in RLHF_RUNGS:
                policy.call(run_rlhf_rung, tag, RLHF_RUNGS[tag.strip()],
                            retry_evidence=evidence, before_attempt=attempt)
            else:
                policy.call(run_rung, tag, retry_evidence=evidence,
                            before_attempt=attempt, **RUNGS[tag.strip()])
        except Exception as e:  # noqa: BLE001 — keep laddering past OOMs
            row = {"tag": tag, "error": f"{type(e).__name__}: {str(e)[:300]}"}
            cls = classify_failure(e)
            if cls is not None:
                # structured blocked row: the failure class + full retry
                # history, machine-readable for PERF.md's envelope table
                row["blocked"] = cls
            row.update(policy.evidence())
            cfg_ov = RUNGS.get(tag.strip(), {}).get("cfg_overrides", {})
            if cfg_ov.get("moe_num_experts"):
                # MoE error rows still carry their route evidence (a failed
                # rung must be attributable to the route that failed it)
                class _C:  # minimal cfg shim for the evidence helper
                    moe_num_experts = cfg_ov["moe_num_experts"]
                    moe_route = cfg_ov.get("moe_route")
                row.update(moe_route_evidence(_C))
            print(json.dumps(row), flush=True)
            traceback.print_exc(file=sys.stderr)
    print("# DONE", flush=True)


if __name__ == "__main__":
    main()
