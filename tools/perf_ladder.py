"""Bench the config ladder's larger rungs on the real chip, one clean-exit
process. NEVER wrap this in `timeout` and never kill it — a killed TPU
process wedges the axon tunnel claim (PERF.md wedge #3: a 760m fused-10
compile alone can exceed 25 min). The script instead checks an INTERNAL
deadline between rungs and exits cleanly; a rung whose compile is in
flight is allowed to finish. Each rung is try/except-isolated; results
print as they land.

Run: python tools/perf_ladder.py            (background it; poll stdout)
Env: LADDER=760m_mb4,760m_mb8,xl_offload_mb1  (comma list; default 760m)
     LADDER_DEADLINE=3600  (seconds; checked between rungs only)
"""
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

try:
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_comp_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

import deepspeed_tpu
from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

SEQ = 1024


def run_rung(tag, model_name, mb, fused=10, offload=False, steps=None):
    t_start = time.time()
    cfg = get_gpt2_config(model_name, n_positions=SEQ, remat=True,
                          attention_backend="flash", dtype=jnp.bfloat16,
                          vocab_size=50304, embed_onehot_grad=True)
    model = GPT2LMHeadModel(cfg)
    ds = {
        "train_batch_size": mb,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10**9,
    }
    if offload:
        ds["zero_optimization"] = {
            "stage": 2,
            "offload_optimizer": {"device": "cpu", "pin_memory": True},
        }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds)
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (mb, SEQ)).astype(np.int32)}
    engine.initialize_state(batch)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(engine.state.params))
    if offload:
        # host-driven schedule: per-step dispatch is the real path here
        n = steps or 3
        engine.train_batch(batch)  # warmup/compile
        jax.block_until_ready(engine.state.params)
        t0 = time.time()
        for _ in range(n):
            engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        dt, n_steps = time.time() - t0, n
    else:
        stack = {"input_ids": np.broadcast_to(batch["input_ids"],
                                              (fused,) + batch["input_ids"].shape)}
        engine.train_batches(stack)
        jax.block_until_ready(engine.state.params)
        t0 = time.time()
        engine.train_batches(stack)
        engine.train_batches(stack)
        jax.block_until_ready(engine.state.params)
        dt, n_steps = time.time() - t0, 2 * fused
    compile_s = time.time() - t_start - dt
    tok = mb * SEQ * n_steps / dt
    tflops = 6.0 * n_params * tok / 1e12
    print(json.dumps({"tag": tag, "params_m": round(n_params / 1e6, 1),
                      "mb": mb, "step_ms": round(dt / n_steps * 1e3, 1),
                      "tokens_per_s": round(tok, 1), "tflops": round(tflops, 2),
                      "vs_baseline": round(tflops / 64.0, 3),
                      "compile_s": round(compile_s, 1)}), flush=True)


RUNGS = {
    "760m_mb4": dict(model_name="760m", mb=4),
    "760m_mb8": dict(model_name="760m", mb=8),
    "xl_offload_mb1": dict(model_name="xl", mb=1, offload=True, steps=2),
    "xl_offload_mb4": dict(model_name="xl", mb=4, offload=True, steps=2),
}


def main():
    deadline = time.time() + int(os.environ.get("LADDER_DEADLINE", "3600"))
    want = os.environ.get("LADDER", "760m_mb4,760m_mb8").split(",")
    print(f"# ladder seq={SEQ}: {want}", flush=True)
    for tag in want:
        if time.time() > deadline:
            print(f"# deadline reached, skipping {tag} onward", flush=True)
            break
        try:
            run_rung(tag, **RUNGS[tag.strip()])
        except Exception as e:  # noqa: BLE001 — keep laddering past OOMs
            print(json.dumps({"tag": tag, "error": f"{type(e).__name__}: {str(e)[:300]}"}),
                  flush=True)
            traceback.print_exc(file=sys.stderr)
    print("# DONE", flush=True)


if __name__ == "__main__":
    main()
