"""Separate tunnel dispatch latency from device compute.

a) RTT probe: tiny chained jit calls — per-call time ≈ dispatch latency.
b) fwd chained: 350M fwd, each call consuming the previous output.
c) fwd scanned: same work, ONE dispatch running a fori_loop on device.
If (b) >> (c), host dispatch latency dominates the per-step numbers.
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
from deepspeed_tpu.models.gpt2 import cross_entropy_loss

MB, SEQ, N = 4, 1024, 10

# a) RTT probe
f = jax.jit(lambda x: x * 1.000001 + 1.0)
x = jnp.float32(0)
x = f(x); jax.block_until_ready(x)
t0 = time.time()
for _ in range(20):
    x = f(x)
jax.block_until_ready(x)
print(f"rtt_per_call_ms {(time.time()-t0)/20*1e3:.2f}", flush=True)

cfg = get_gpt2_config("350m", n_positions=SEQ, remat=True,
                      attention_backend="flash", dtype=jnp.bfloat16)
model = GPT2LMHeadModel(cfg)
rng = np.random.default_rng(0)
ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (MB, SEQ)), jnp.int32)
params = jax.jit(lambda k: model.init(k, ids[:1, :8])["params"])(jax.random.PRNGKey(0))
params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
labels = jnp.concatenate([ids[:, 1:], jnp.full((MB, 1), -100, jnp.int32)], axis=1)


def loss_fn(p, ids, bias):
    logits = model.apply({"params": p}, ids)
    return cross_entropy_loss(logits, labels) + bias


# b) chained host dispatches
g = jax.jit(loss_fn)
acc = jnp.float32(0)
out = g(params, ids, acc); jax.block_until_ready(out)
t0 = time.time()
for _ in range(N):
    acc = g(params, ids, acc * 1e-9)
jax.block_until_ready(acc)
print(f"fwd_chained_ms {(time.time()-t0)/N*1e3:.1f}", flush=True)

# c) one dispatch, fori_loop on device
def scanned(p, ids):
    def body(i, acc):
        return loss_fn(p, ids, acc * 1e-9)
    return jax.lax.fori_loop(0, N, body, jnp.float32(0))

s = jax.jit(scanned)
out = s(params, ids); jax.block_until_ready(out)
t0 = time.time()
out = s(params, ids)
jax.block_until_ready(out)
print(f"fwd_scanned_ms {(time.time()-t0)/N*1e3:.1f}", flush=True)

# d) grad, chained vs scanned
grad_fn = jax.grad(loss_fn)

def gsum(p, ids, acc):
    gr = grad_fn(p, ids, acc * 1e-9)
    return sum(jnp.sum(l.astype(jnp.float32)) for l in jax.tree.leaves(gr))

gj = jax.jit(gsum)
acc = jnp.float32(0)
out = gj(params, ids, acc); jax.block_until_ready(out)
t0 = time.time()
for _ in range(N):
    acc = gj(params, ids, acc)
jax.block_until_ready(acc)
print(f"grad_chained_ms {(time.time()-t0)/N*1e3:.1f}", flush=True)

def gscanned(p, ids):
    return jax.lax.fori_loop(0, N, lambda i, acc: gsum(p, ids, acc), jnp.float32(0))

gs = jax.jit(gscanned)
out = gs(params, ids); jax.block_until_ready(out)
t0 = time.time()
out = gs(params, ids)
jax.block_until_ready(out)
print(f"grad_scanned_ms {(time.time()-t0)/N*1e3:.1f}", flush=True)
