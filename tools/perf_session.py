"""One clean-exit TPU perf session: measures the engine step per-dispatch
vs fused-scan, prints each result immediately, exits cleanly.

Run: python tools/perf_session.py          (background it; poll stdout)
NEVER wrap in `timeout` and never kill it mid-run — a killed TPU process
wedges the axon tunnel claim for hours (PERF.md wedges #3/#4). Note the
per-dispatch numbers it prints are KNOWN-FAKE on the axon tunnel (the
dedupe cache, PERF.md session 3); only the fused-scan timings count —
this tool's A/B already answered that question, it remains as a
diagnostic.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

MODEL = os.environ.get("BENCH_MODEL", "350m")
MB = int(os.environ.get("BENCH_MICRO_BS", "4"))
SEQ = int(os.environ.get("BENCH_SEQ", "1024"))
FUSED = int(os.environ.get("BENCH_FUSED_STEPS", "10"))


def report(tag, steps, dt, n_params, cfg=None):
    from bench_core import flops_per_token_from_cfg, model_flops_per_token
    tok = MB * SEQ * steps / dt
    fpt = (flops_per_token_from_cfg(n_params, cfg, SEQ) if cfg is not None
           else model_flops_per_token(n_params))
    print(json.dumps({"tag": tag, "step_ms": round(dt / steps * 1e3, 1),
                      "tokens_per_s": round(tok, 1),
                      "tflops": round(fpt * tok / 1e12, 2)}), flush=True)


def main():
    cfg = get_gpt2_config(MODEL, n_positions=SEQ, remat=True,
                          attention_backend="flash", dtype=jnp.bfloat16,
                          embed_onehot_grad=os.environ.get("BENCH_EMBED_ONEHOT", "1") == "1")
    model = GPT2LMHeadModel(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": MB,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10**9,
    })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (MB, SEQ)).astype(np.int32)}
    engine.initialize_state(batch)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(engine.state.params))
    print(f"# {MODEL} params={n_params/1e6:.1f}M mb={MB} seq={SEQ}", flush=True)

    # 1) per-dispatch loop (bench.py default path)
    for _ in range(2):
        engine.train_batch(batch)
    jax.block_until_ready(engine.state.params)
    t0 = time.time()
    for _ in range(10):
        engine.train_batch(batch)
    jax.block_until_ready(engine.state.params)
    report("per_dispatch", 10, time.time() - t0, n_params, cfg)

    # 2) fused scan: FUSED steps per dispatch
    stack = {"input_ids": np.broadcast_to(batch["input_ids"],
                                          (FUSED,) + batch["input_ids"].shape)}
    engine.train_batches(stack)
    jax.block_until_ready(engine.state.params)
    t0 = time.time()
    engine.train_batches(stack)
    jax.block_until_ready(engine.state.params)
    report(f"fused_{FUSED}", FUSED, time.time() - t0, n_params, cfg)

    # run the fused dispatch twice more for variance
    t0 = time.time()
    engine.train_batches(stack)
    engine.train_batches(stack)
    jax.block_until_ready(engine.state.params)
    report(f"fused_{FUSED}_x2", 2 * FUSED, time.time() - t0, n_params, cfg)

    print("# DONE", flush=True)


if __name__ == "__main__":
    main()
