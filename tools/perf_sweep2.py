"""One clean-exit TPU sweep over the single-chip perf levers (micro-batch,
one-hot embedding backward, lane-aligned vocab, fused LM-head loss). Each
config is an independent engine build inside THIS process (try/except per
config, so an OOM doesn't lose earlier results); results print as they
land. NEVER wrap in `timeout` and never kill mid-run — a killed TPU
process wedges the axon tunnel claim.

Run: python tools/perf_sweep2.py   (background it; poll stdout)
"""
import json
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_core import build_engine, enable_compile_cache, report, time_fused

SEQ = 1024
FUSED = 10
MODEL = os.environ.get("BENCH_MODEL", "350m")


def run_config(tag, mb, vocab=None, onehot=False, xent_chunk=0):
    overrides = {}
    if vocab:
        overrides["vocab_size"] = vocab
    if onehot:
        overrides["embed_onehot_grad"] = True
    if xent_chunk:
        overrides["fused_head_loss_chunk"] = xent_chunk
    engine, batch, n_params, cfg = build_engine(MODEL, mb, SEQ, **overrides)
    n_steps, dt, compile_s = time_fused(engine, batch, fused=FUSED)
    report(tag, mb, SEQ, n_params, n_steps, dt, compile_s, cfg=cfg)


def main():
    enable_compile_cache()
    print(f"# sweep2 model={MODEL} seq={SEQ} fused={FUSED}", flush=True)
    configs = [
        ("mb8_fusedxent", dict(mb=8, vocab=50304, onehot=True, xent_chunk=1024)),
        ("mb16_fusedxent", dict(mb=16, vocab=50304, onehot=True, xent_chunk=1024)),
    ]
    for tag, kw in configs:
        try:
            run_config(tag, **kw)
        except Exception as e:  # noqa: BLE001 — keep sweeping past OOMs
            print(json.dumps({"tag": tag, "error": f"{type(e).__name__}: {str(e)[:300]}"}),
                  flush=True)
            traceback.print_exc(file=sys.stderr)
    print("# DONE", flush=True)


if __name__ == "__main__":
    main()
