"""One clean-exit TPU sweep over the round-3 perf levers: micro-batch,
one-hot embedding backward, lane-aligned vocab. Each config is an
independent engine build inside THIS process (try/except per config, so
an OOM on mb=16 doesn't lose the earlier results); results print
immediately. Never kill this process — a killed TPU process wedges the
axon tunnel claim. Budget: ~4 compiles; exit is clean even on failure.

Run: timeout 2800 python tools/perf_sweep2.py
"""
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

try:
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_comp_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

import deepspeed_tpu
from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

SEQ = 1024
FUSED = 10
MODEL = os.environ.get("BENCH_MODEL", "350m")


def run_config(tag, mb, vocab=None, onehot=False, remat=True, xent_chunk=0):
    t_start = time.time()
    overrides = {}
    if vocab:
        overrides["vocab_size"] = vocab
    if onehot:
        overrides["embed_onehot_grad"] = True
    if xent_chunk:
        overrides["fused_head_loss_chunk"] = xent_chunk
    cfg = get_gpt2_config(MODEL, n_positions=SEQ, remat=remat,
                          attention_backend="flash", dtype=jnp.bfloat16,
                          **overrides)
    model = GPT2LMHeadModel(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": mb,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 0},
        "steps_per_print": 10**9,
    })
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (mb, SEQ)).astype(np.int32)}
    engine.initialize_state(batch)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(engine.state.params))
    stack = {"input_ids": np.broadcast_to(batch["input_ids"],
                                          (FUSED,) + batch["input_ids"].shape)}
    engine.train_batches(stack)  # compile + warmup
    jax.block_until_ready(engine.state.params)
    compile_s = time.time() - t_start
    t0 = time.time()
    engine.train_batches(stack)
    engine.train_batches(stack)
    jax.block_until_ready(engine.state.params)
    dt = time.time() - t0
    steps = 2 * FUSED
    tok = mb * SEQ * steps / dt
    tflops = 6.0 * n_params * tok / 1e12
    print(json.dumps({"tag": tag, "mb": mb, "step_ms": round(dt / steps * 1e3, 1),
                      "tokens_per_s": round(tok, 1), "tflops": round(tflops, 2),
                      "compile_s": round(compile_s, 1)}), flush=True)
    return tflops


def main():
    print(f"# sweep2 model={MODEL} seq={SEQ} fused={FUSED}", flush=True)
    configs = [
        ("mb8_fusedxent", dict(mb=8, vocab=50304, onehot=True, xent_chunk=1024)),
        ("mb16_fusedxent", dict(mb=16, vocab=50304, onehot=True, xent_chunk=1024)),
    ]
    for tag, kw in configs:
        try:
            run_config(tag, **kw)
        except Exception as e:  # noqa: BLE001 — keep sweeping past OOMs
            print(json.dumps({"tag": tag, "error": f"{type(e).__name__}: {e}"}),
                  flush=True)
            traceback.print_exc(file=sys.stderr)
    print("# DONE", flush=True)


if __name__ == "__main__":
    main()
