"""RLHF hybrid-engine throughput bench — the evidence class behind the
reference's DeepSpeed-Chat claims (``blogs/deepspeed-chat/README.md:30``
"15x faster"; per-model train-time tables ``:38``). Their cost is split
across exactly the phases measured here:

1. **rollout generation** (serving layout; the hybrid engine reshards the
   LIVE training params into inference TP and runs the jitted decode loop),
2. **train<->serve switch latency** (reference: gather/scatter of ZeRO
   shards per swap, ``hybrid_engine.py``; here: the param-layout reshard +
   program swap, amortized by the jit cache),
3. **policy update step** (REINFORCE surrogate loss through the production
   ZeRO train step).

One JSON line: per-phase times + end-to-end RLHF iterations/s.

Run: python tools/rlhf_bench.py     (background; clean-exit; NEVER
     timeout-wrap on the tunnel)
Env: RLHF_MODEL=350m RLHF_BATCH=8 RLHF_PROMPT=128 RLHF_NEW=128
     RLHF_ITERS=3 RLHF_ZERO=0
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

MODEL = os.environ.get("RLHF_MODEL", "350m")
BATCH = int(os.environ.get("RLHF_BATCH", "8"))
PROMPT = int(os.environ.get("RLHF_PROMPT", "128"))
NEW = int(os.environ.get("RLHF_NEW", "128"))
ITERS = int(os.environ.get("RLHF_ITERS", "3"))
ZERO = int(os.environ.get("RLHF_ZERO", "0"))


def main():
    import jax
    import jax.numpy as jnp

    from bench_core import enable_compile_cache

    enable_compile_cache()
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config(MODEL, n_positions=PROMPT + NEW, dtype=jnp.bfloat16,
                          remat=True,
                          attention_backend="flash"
                          if jax.default_backend() in ("tpu", "axon") else "xla")
    model = GPT2LMHeadModel(cfg)

    def loss_fn(logits, batch):
        tok = batch["rollouts"]
        adv = batch["advantage"]
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logp, tok[:, 1:, None], axis=-1)[..., 0]
        mask = jnp.arange(tok.shape[1] - 1)[None, :] >= (PROMPT - 1)
        return -jnp.mean(adv[:, None] * tgt * mask)

    ds = {"train_batch_size": BATCH,
          "optimizer": {"type": "AdamW", "params": {"lr": 1e-5}},
          "bf16": {"enabled": True},
          "gradient_clipping": 1.0,
          "zero_optimization": {"stage": ZERO},
          "hybrid_engine": {"enabled": True},
          "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds,
                                               loss_fn=loss_fn)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, (BATCH, PROMPT)).astype(np.int32)
    # state must exist before the first generate(): the hybrid engine
    # reshards the LIVE training params into the serving layout
    example = {"input_ids": np.zeros((BATCH, PROMPT + NEW), np.int32),
               "rollouts": np.zeros((BATCH, PROMPT + NEW), np.int32),
               "advantage": np.zeros((BATCH,), np.float32)}
    engine.initialize_state(example)

    def one_iter():
        t0 = time.time()
        rollouts = np.asarray(engine.generate(prompts, max_new_tokens=NEW))
        t_gen = time.time() - t0
        reward = (rollouts[:, PROMPT:] % 2 == 0).mean(axis=1).astype(np.float32)
        adv = reward - reward.mean()
        t0 = time.time()
        batch = {"input_ids": rollouts[:, : PROMPT + NEW],
                 "rollouts": rollouts[:, : PROMPT + NEW],
                 "advantage": adv}
        loss = engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        t_train = time.time() - t0
        return t_gen, t_train, float(jnp.asarray(loss))

    # warmup: compiles the serve programs, the reshard, and the train step
    t0 = time.time()
    one_iter()
    warm_s = time.time() - t0
    gens, trains = [], []
    t_all = time.time()
    for _ in range(ITERS):
        t_gen, t_train, loss = one_iter()
        gens.append(t_gen)
        trains.append(t_train)
    dt = time.time() - t_all
    stats = engine.hybrid_stats() if hasattr(engine, "hybrid_stats") else {}
    print(json.dumps({
        "backend": jax.default_backend(),
        "model": MODEL, "batch": BATCH, "prompt": PROMPT, "new": NEW,
        "warmup_s": round(warm_s, 2),
        "gen_s_per_iter": round(float(np.mean(gens)), 3),
        "gen_tokens_per_s": round(BATCH * NEW / float(np.mean(gens)), 1),
        "train_s_per_iter": round(float(np.mean(trains)), 3),
        "rlhf_iters_per_s": round(ITERS / dt, 4),
        "hybrid_stats": {k: round(float(v), 4) for k, v in stats.items()},
    }), flush=True)


if __name__ == "__main__":
    main()
