"""RLHF hybrid-engine throughput bench — graft-rlhf A/B edition.

The reference's DeepSpeed-Chat claims (``blogs/deepspeed-chat/README.md:30``
"15x faster") price exactly the phases measured here, but its hybrid
engine runs them as *serial offline phases*: generate() blocks the
learner, and every rollout in a static batch decodes to the longest
budget in its cohort. PR 20 rebuilds the generation phase on the
continuous scheduler, so this bench is now an A/B on the SAME prompt
trace (deterministic indexed prompts + per-rollout token budgets):

- ``off`` — the serial baseline: generate-then-train per learner batch,
  static batching (the whole cohort decodes to its max budget, outputs
  trimmed to per-rollout budgets so both arms bank identical experience).
- ``on``  — the in-flight loop (``runtime/rlhf``): prompts stream into a
  ContinuousBatchingScheduler, finished slots re-admit immediately, the
  learner interleaves at decode-tick granularity, weight sync is
  planner-priced + digest-verified per ``RLHF_SYNC_EVERY`` learner steps.

Goodput = banked experience tokens / wall seconds at EQUAL experience
count (same budgets, same learner-step count). ``ab`` mode runs both and
emits a ratio row — the ``>= 1.3x`` acceptance evidence.

Telemetry (RLHF_TELEMETRY=dir): the on-arm stamps two run headers in
separate sinks — scope ``rlhf_rollout`` with the scheduler's
``serving_static_price()`` (the graft-calibrate fit source) and scope
``rlhf_learner`` with the train step's static price; both carry the
``rlhf_overlap`` separation marker ``collect_samples`` keys its
mixed-run refusal on.

Run: python tools/rlhf_bench.py     (background; clean-exit; NEVER
     timeout-wrap on the tunnel)
Env: RLHF_MODE=ab|on|off RLHF_MODEL=test RLHF_BATCH=8 RLHF_PROMPT=16
     RLHF_NEW=32 RLHF_ROLLOUTS=32 RLHF_SLOTS=8 RLHF_SYNC_EVERY=1
     RLHF_ZERO=3 RLHF_TICK_SLEEP_MS=0 RLHF_TELEMETRY=
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

MODE = os.environ.get("RLHF_MODE", "ab")
MODEL = os.environ.get("RLHF_MODEL", "test")
BATCH = int(os.environ.get("RLHF_BATCH", "8"))          # learner batch
PROMPT = int(os.environ.get("RLHF_PROMPT", "16"))
NEW = int(os.environ.get("RLHF_NEW", "32"))             # max token budget
ROLLOUTS = int(os.environ.get("RLHF_ROLLOUTS", "32"))
SLOTS = int(os.environ.get("RLHF_SLOTS", str(BATCH)))
SYNC_EVERY = int(os.environ.get("RLHF_SYNC_EVERY", "1"))
ZERO = int(os.environ.get("RLHF_ZERO", "3"))
TICK_SLEEP_MS = float(os.environ.get("RLHF_TICK_SLEEP_MS", "0"))
TELEMETRY = os.environ.get("RLHF_TELEMETRY", "")


def budget(i: int) -> int:
    """Deterministic per-rollout token budget in [max(4, NEW//4), NEW] —
    the long-tail mix that makes static cohorts pay max-budget decode for
    every member while the continuous scheduler re-admits freed slots."""
    lo = max(4, NEW // 4)
    return lo + (i * 7919) % (NEW - lo + 1)


def prompt_tokens(i: int, vocab: int) -> np.ndarray:
    r = np.random.RandomState(1234 + i)
    return r.randint(0, vocab, size=(PROMPT,)).astype(np.int32)


def build_engine(jnp):
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    import jax
    cfg = get_gpt2_config(MODEL, n_positions=PROMPT + NEW, dtype=None)
    model = GPT2LMHeadModel(cfg)

    def loss_fn(logits, batch):
        tok = batch["rollouts"]
        adv = batch["advantage"]
        mask = batch["mask"].astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(logp, tok[:, 1:, None], axis=-1)[..., 0]
        return -(adv[:, None] * tgt * mask[:, 1:]).sum() \
            / jnp.maximum(mask[:, 1:].sum(), 1.0)

    ds = {"train_batch_size": BATCH,
          "optimizer": {"type": "AdamW", "params": {"lr": 1e-5}},
          "gradient_clipping": 1.0,
          "zero_optimization": {"stage": ZERO,
                                **({"stage3_param_persistence_threshold": 0}
                                   if ZERO == 3 else {})},
          "hybrid_engine": {"enabled": True, "max_out_tokens": PROMPT + NEW,
                            "inference_tp_size": 1},
          "steps_per_print": 10**9}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds,
                                               loss_fn=loss_fn)
    example = _pad_batch([(np.zeros(PROMPT, np.int32), np.zeros(0, np.int32))]
                         * BATCH, np.zeros(BATCH, np.float32))
    engine.initialize_state(example)
    return engine, cfg


def _pad_batch(pairs, adv):
    """(prompt, output) pairs -> fixed-width learner batch with a loss
    mask over the generated positions (identical shape both arms)."""
    width = PROMPT + NEW
    toks = np.zeros((len(pairs), width), np.int32)
    mask = np.zeros((len(pairs), width), np.float32)
    for j, (p, o) in enumerate(pairs):
        seq = np.concatenate([np.asarray(p, np.int32),
                              np.asarray(o, np.int32)])[:width]
        toks[j, :len(seq)] = seq
        mask[j, len(p):len(seq)] = 1.0
    return {"input_ids": toks, "rollouts": toks, "advantage": adv,
            "mask": mask}


def _advantage(pairs):
    reward = np.asarray([(np.asarray(o) % 2 == 0).mean() if len(o) else 0.0
                         for _, o in pairs], np.float32)
    return reward - reward.mean()


def _learner_batch(pairs):
    return _pad_batch(pairs, _advantage(pairs))


def _sync_summary(log):
    if not log:
        return None
    last = log[-1]
    return {"syncs": len(log),
            "generation": last.get("generation"),
            "gather_bytes": last.get("gather_bytes"),
            "total_bytes": last.get("total_bytes"),
            "digest_verified": bool(last.get("digest")),
            "error": last.get("error")}


def _telemetry(job, scope, overlap, static_price):
    if not TELEMETRY:
        return None
    from deepspeed_tpu.runtime.config import TelemetryConfig
    from deepspeed_tpu.runtime.telemetry import RuntimeTelemetry
    import jax
    t = RuntimeTelemetry(TelemetryConfig(enabled=True, output_path=TELEMETRY,
                                         job_name=job))
    t.write_run_header(
        {"bench": "rlhf_bench", "model": MODEL, "backend": jax.default_backend(),
         "scope": scope, "rlhf_overlap": overlap,
         "batch": BATCH, "prompt": PROMPT, "new": NEW},
        static_price=static_price)
    return t


def run_off(engine, cfg):
    """Serial baseline: static generate-then-train, cohort-max decode."""
    import jax
    total = ROLLOUTS
    n_batches = total // BATCH

    def cohort(k, timed):
        idxs = list(range(k * BATCH, (k + 1) * BATCH))
        prompts = np.stack([prompt_tokens(i, cfg.vocab_size) for i in idxs])
        maxb = max(budget(i) for i in idxs)
        t0 = time.perf_counter()
        out = np.asarray(engine.generate(prompts, max_new_tokens=maxb))
        gen_s = time.perf_counter() - t0
        pairs = [(prompts[j], out[j, PROMPT:PROMPT + budget(i)])
                 for j, i in enumerate(idxs)]
        t0 = time.perf_counter()
        loss = float(engine.train_batch(_learner_batch(pairs)))
        jax.block_until_ready(engine.state.params)
        train_s = time.perf_counter() - t0
        if TICK_SLEEP_MS and timed:
            # emulated-device regime: the serial arm's generate ticks run
            # on-device too — maxb decode ticks, nothing overlapped
            time.sleep(TICK_SLEEP_MS / 1e3 * maxb)
        return pairs, gen_s, train_s, loss

    cohort(0, timed=False)  # warmup: compiles generate + reshard + train
    t_all = time.perf_counter()
    gen_s = train_s = 0.0
    tokens = 0
    losses = []
    steps = 0
    for k in range(n_batches):
        pairs, g, t, loss = cohort(k, timed=True)
        gen_s += g
        train_s += t
        tokens += sum(len(o) for _, o in pairs)
        losses.append(loss)
        steps += 1
    wall = time.perf_counter() - t_all
    return {"mode": "rlhf_overlap_off", "rollouts": n_batches * BATCH,
            "experience_tokens": tokens, "wall_s": round(wall, 3),
            "goodput_tok_s": round(tokens / wall, 2),
            "gen_s": round(gen_s, 3), "train_s": round(train_s, 3),
            "learner_steps": steps, "loss_last": losses[-1],
            "weight_sync": _sync_summary(engine.weight_sync_log)}


def run_on(engine, cfg):
    """In-flight loop: continuous scheduler + tick-interleaved learner."""
    from deepspeed_tpu.inference.serving import Request, ServingConfig
    from deepspeed_tpu.runtime.rlhf import RolloutConfig, RolloutLoop

    def prompt_fn(i):
        return Request(prompt=prompt_tokens(i, cfg.vocab_size),
                       max_new_tokens=budget(i))

    def make_batch(exps):
        pairs = [(np.asarray(e.prompt, np.int32),
                  np.asarray(e.output, np.int32)) for e in exps]
        return _learner_batch(pairs)

    scfg = ServingConfig(slots=SLOTS, prefill_chunk=PROMPT)
    warm = RolloutLoop(engine, prompt_fn, make_batch,
                       RolloutConfig(train_batch_size=BATCH,
                                     total_rollouts=BATCH, sync_every=1),
                       serving_config=scfg)
    warm.run(max_ticks=10**6)  # warmup: serve programs + train + sync

    telemetry = _telemetry("rlhf_rollout", "rlhf_rollout", "on",
                           warm.scheduler.serving_static_price())
    learner_t = None
    if TELEMETRY:
        from deepspeed_tpu.analysis.cost import static_price_from_programs
        try:
            price = static_price_from_programs(
                engine.traced_programs(
                    _learner_batch([(np.zeros(PROMPT, np.int32),
                                     np.zeros(0, np.int32))] * BATCH),
                    lower=False))
        except Exception as e:
            price = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        learner_t = _telemetry("rlhf_learner", "rlhf_learner", "on", price)

    loop = RolloutLoop(engine, prompt_fn, make_batch,
                       RolloutConfig(train_batch_size=BATCH,
                                     total_rollouts=ROLLOUTS,
                                     sync_every=SYNC_EVERY,
                                     tick_sleep_ms=TICK_SLEEP_MS),
                       serving_config=scfg, telemetry=telemetry,
                       learner_telemetry=learner_t)
    t0 = time.perf_counter()
    res = loop.run(max_ticks=10**7)
    wall = time.perf_counter() - t0
    for t in (telemetry, learner_t):
        if t is not None:
            t.close()
    stats = res["scheduler_stats"]
    tokens = stats["generated_tokens"]
    return {"mode": "rlhf_overlap_on", "rollouts": res["experience_consumed"],
            "experience_tokens": tokens, "wall_s": round(wall, 3),
            "goodput_tok_s": round(tokens / wall, 2),
            "learner_steps": res["learner_steps"],
            "loss_last": res["losses"][-1]["loss"] if res["losses"] else None,
            "learner_steps_overlapped":
                stats["rollout"]["learner_steps_overlapped"],
            "weight_sync_generation": res["weight_sync_generation"],
            "weight_sync": _sync_summary(res["sync_evidence"]),
            "ticks": stats["ticks"]}


def main():
    import jax
    import jax.numpy as jnp

    from bench_core import enable_compile_cache
    enable_compile_cache()

    assert ROLLOUTS % BATCH == 0, "RLHF_ROLLOUTS must be a multiple of RLHF_BATCH"
    common = {"backend": jax.default_backend(), "model": MODEL,
              "batch": BATCH, "prompt": PROMPT, "new": NEW,
              "rollouts": ROLLOUTS, "slots": SLOTS,
              "sync_every": SYNC_EVERY, "tick_sleep_ms": TICK_SLEEP_MS}
    rows = []
    if MODE in ("off", "ab"):
        engine, cfg = build_engine(jnp)
        rows.append({**common, **run_off(engine, cfg)})
        print(json.dumps(rows[-1]), flush=True)
    if MODE in ("on", "ab"):
        engine, cfg = build_engine(jnp)
        rows.append({**common, **run_on(engine, cfg)})
        print(json.dumps(rows[-1]), flush=True)
    if MODE == "ab":
        off = next(r for r in rows if r["mode"] == "rlhf_overlap_off")
        on = next(r for r in rows if r["mode"] == "rlhf_overlap_on")
        assert on["experience_tokens"] == off["experience_tokens"], \
            (on["experience_tokens"], off["experience_tokens"])
        print(json.dumps({**common, "mode": "rlhf_ab",
                          "experience_tokens": on["experience_tokens"],
                          "goodput_off": off["goodput_tok_s"],
                          "goodput_on": on["goodput_tok_s"],
                          "speedup": round(on["goodput_tok_s"]
                                           / off["goodput_tok_s"], 3)}),
              flush=True)


if __name__ == "__main__":
    main()
