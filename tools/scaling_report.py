"""ZeRO-3 weak-scaling report over virtual meshes, 8 → 256 chips.

BASELINE.md's primary metric includes "ZeRO-3 scaling efficiency 8→256
chips (GPT-2-XL)". Real multi-chip hardware is not available here, but the
thing that decides weak-scaling efficiency — what each chip must move over
ICI per step — IS checkable without chips: compile the ZeRO-3 train step
for N virtual CPU devices and read the collective payload bytes out of the
SPMD-partitioned HLO. Weak scaling holds when per-chip payload stays ~flat
as N grows (each chip always gathers the full parameter set and
reduce-scatters the full gradient set, independent of N — the reference's
ZeRO-3 has the same invariant, ``stage3.py:1176`` reduce_scatter over the
whole DP group).

Each N runs in a fresh subprocess (device count is fixed at jax import);
the parent prints one JSON line per N plus a verdict. Pure-CPU work — safe
to run with the TPU tunnel down.

Run: python tools/scaling_report.py          [MODEL=125m SEQ=128 MB_PER_CHIP=1]
     Default meshes 8,16,64,256. MESHES=8,64,512 reaches 512 virtual
     chips — supported, but XLA's 512-partition CPU compile of the 125m
     step runs >30 min on a 14-core host (use MODEL=test SEQ=64 for a
     tractable 512-way check; the invariant is scale-free).
"""
import json
import os
import subprocess
import sys

_DEFAULT_MESHES = "8,16,64" if int(os.environ.get("MOE", "0")) else "8,16,64,256"
# MoE default stops at 64: the [G,S,E] gating-mask payload is inherent and
# ~linear in total experts (E = k*N), so past the calibrated 8->64 span the
# verdict would flag healthy plans; override MESHES to look further.
MESHES = [int(n) for n in os.environ.get("MESHES", _DEFAULT_MESHES).split(",")]
MODEL = os.environ.get("MODEL", "125m")
SEQ = int(os.environ.get("SEQ", "128"))
MB_PER_CHIP = int(os.environ.get("MB_PER_CHIP", "1"))
# lane-aligned AND 256-divisible vocab so the fsdp axis always divides
VOCAB = int(os.environ.get("VOCAB", "50432"))
# TP=k carves a fixed tensor axis out of each mesh (the LLaMA + ZeRO++
# ladder shape: fsdp grows, tensor stays constant); per-chip payload must
# still stay flat as the fsdp factor grows
TP = int(os.environ.get("TP", "1"))
# MOE=k switches to expert-parallel weak scaling (the GPT-MoE ladder
# rung): the mesh axis is `expert` instead of `fsdp`, with k local
# experts per chip (total experts = k * N). Flatness here means the a2a
# dispatch + replicated-dense allreduce per chip don't grow with N.
MOE = int(os.environ.get("MOE", "0"))
# OFFLOAD=1 switches the fsdp sweep to the ZeRO-Infinity step (stage 3 +
# offload_param cpu): params rest host-side and stream per layer — the
# per-chip ICI payload must stay as flat as the dense stage-3 step's
# (streaming changes WHERE params rest, not what chips exchange)
OFFLOAD = int(os.environ.get("OFFLOAD", "0"))

CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r}); sys.path.insert(0, {repo!r} + "/tests")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config
from deepspeed_tpu.parallel.topology import MeshTopology
from unit.runtime.test_qcomm import collective_payload_bytes

n = {n}
tp = {tp}
moe = {moe}
offload = {offload}
t0 = time.time()
extra = dict(moe_num_experts=moe * n, moe_layer_freq=2, moe_k=1) if moe else {{}}
cfg = get_gpt2_config({model!r}, n_positions={seq}, vocab_size={vocab}, **extra)
topo = MeshTopology(expert=n) if moe else MeshTopology(fsdp=n // tp, tensor=tp)
zero_cfg = {{"stage": 1 if moe else 3, "stage3_param_persistence_threshold": 0}}
if offload:
    zero_cfg["offload_param"] = {{"device": "cpu"}}
engine, _, _, _ = deepspeed_tpu.initialize(
    model=GPT2LMHeadModel(cfg), topology=topo,
    config={{"train_batch_size": {mb} * (n if moe else n // tp),
            "optimizer": {{"type": "AdamW", "params": {{"lr": 1e-3}}}},
            "bf16": {{"enabled": True}},
            "zero_optimization": zero_cfg}})
rng = np.random.default_rng(0)
batch = {{"input_ids": rng.integers(0, cfg.vocab_size,
                                    ({mb} * (n if moe else n // tp), {seq})).astype(np.int32)}}
engine.initialize_state(batch)
hlo = engine.lower_train_step(batch).compile().as_text()
print("RESULT", n, collective_payload_bytes(hlo), round(time.time() - t0, 1))
"""


def run_mesh(n):
    env = {k: v for k, v in os.environ.items() if not k.startswith("PALLAS_AXON")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = CHILD.format(repo=repo, n=n, model=MODEL, seq=SEQ, vocab=VOCAB,
                        mb=MB_PER_CHIP, tp=TP, moe=MOE, offload=OFFLOAD)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=1800)
    for line in r.stdout.splitlines():
        if line.startswith("RESULT"):
            _, n_, payload, secs = line.split()
            return int(payload), float(secs)
    raise RuntimeError(f"mesh {n} failed:\n{r.stderr[-1500:]}")


def main():
    if MOE and TP > 1:
        print(json.dumps({"error": "MOE mode scales the expert axis; combine "
                          "with TP via the config-ladder tests instead"}), flush=True)
        return 2
    if MOE and OFFLOAD:
        print(json.dumps({"error": "MOE mode runs stage 1 (replicated dense + "
                          "expert a2a); offload_param is a stage-3 feature — "
                          "measure them separately"}), flush=True)
        return 2
    results = {}
    for n in MESHES:
        payload, secs = run_mesh(n)
        results[n] = payload
        print(json.dumps({"mesh": n, "tp": TP, "moe": MOE, "offload": OFFLOAD,
                          "per_chip_collective_bytes": payload,
                          "compile_s": secs}), flush=True)
    if len(MESHES) < 2:
        # one mesh measures nothing about scaling — say so, don't pass
        print(json.dumps({"model": MODEL, "weak_scaling_flat": None,
                          "note": "need >=2 mesh sizes to compare"}), flush=True)
        return 2
    base_n = MESHES[0]
    worst = max(results[n] / results[base_n] for n in MESHES[1:])
    # fsdp/TP meshes measure flat at 1.000 (PERF.md r3) — 10% budget total.
    # MoE carries the inherent [G,S,E] gating-mask term (E grows with the
    # mesh): 35% over the calibrated 8->64 span (measured 1.315; the
    # default MoE mesh list stops at 64 for exactly this reason).
    bound = 1.35 if MOE else 1.10
    flat = worst <= bound
    print(json.dumps({"model": MODEL, "weak_scaling_flat": flat, "bound": bound,
                      "max_payload_growth_vs_first": round(worst, 3)}), flush=True)
    return 0 if flat else 1


if __name__ == "__main__":
    sys.exit(main())
