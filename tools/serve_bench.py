"""graft-serve bench: latency under load, not offline throughput.

Replays one Poisson arrival trace at a target QPS through (a) the
continuous in-flight batching scheduler (``inference/serving``) and (b)
the pre-PR-14 static batcher (accumulate a fixed batch, run
``engine.generate``), reporting per-mode p50/p99 time-to-first-token,
p50/p99 per-token latency, and goodput (completed tokens per second of
wall clock at the offered load). Both modes see the SAME trace, so the
comparison row is apples-to-apples: the acceptance claim is that
continuous batching beats static batching on goodput at equal offered
load (PERF.md §PR14).

Run: python tools/serve_bench.py    (background it; poll stdout)
Env: SERVE_MODEL=test|125m|350m...   model family config
     SERVE_MODE=continuous,static   comma list; "both" = the comparison
     SERVE_QPS=4.0                  offered load (Poisson arrivals)
     SERVE_REQUESTS=32              trace length
     SERVE_PROMPT=64 SERVE_NEW=32   tokens per request
     SERVE_NEW_JITTER=0             1 = ragged output budgets: max_new ~
                                    U[NEW/4, NEW] per request (real traces
                                    finish at different lengths — a static
                                    batch decodes to its max while
                                    continuous retires slots early)
     SERVE_LONG_EVERY=0             every Nth request gets a 4x prompt
                                    (continuous-only modes; exercises
                                    chunked prefill under decode load)
     SERVE_SLOTS=8                  decode slots (= static batch size)
     SERVE_CHUNK=16                 prefill chunk (0 = prompt-sized, i.e.
                                    chunked prefill OFF)
     SERVE_SPEC=0 SERVE_SPEC_K=4    speculative decoding (KD student
                                    drafter, half the target's layers)
     SERVE_POOL_TOKENS=0            KV pool budget (0 = slots x context)
     SERVE_POOL_BYTES=0             KV pool BYTE budget (wins over tokens;
                                    the quant A/B's shared-HBM constraint)
     SERVE_WQ=fp                    served weight dtype (fp|int8|int4) for
                                    continuous rows; quant_ab's quant arm
                                    uses int8 unless int4 is set here
     SERVE_KV_QUANT=1               int8 KV pools for continuous rows
                                    (the graft-quant-serve serving default)
  SERVE_MODE may also name "quant_ab": the graft-quant-serve comparison —
  the SAME trace served twice, fp weights + fp KV vs int8 weights + int8
  KV, under the SAME KV byte budget (SERVE_POOL_BYTES), reporting
  blocks-per-GB, goodput ratio at the offered load, and the token-level
  greedy match rate of the quantized arm against fp (PERF.md §PR16).
  SERVE_MODE may also name "prefix_ab" (or pass --prefix-ab): the
  graft-prefix-cache comparison — the SAME trace (use
  SERVE_SHARED_PREFIX for a trace that actually shares prefixes) served
  twice, prefix cache ON vs OFF, at IDENTICAL pool bytes, reporting
  goodput ratio, TTFT p99 per arm, hit rate / cached blocks, and the
  token-level greedy match of the cached arm against the uncached one —
  which must be EXACT: restored KV rows are the same bytes prefill
  would have written (PERF.md §PR19).
     SERVE_SHARED_PREFIX=0           >0 = shared-prefix workload family:
                                    that many template prefixes (each
                                    3/4 of SERVE_PROMPT tokens); request
                                    i takes template i%N + a unique
                                    suffix. Deterministic from
                                    SERVE_SEED, so every arm replays the
                                    identical trace
  SERVE_MODE may also name "fleet" (or pass --fleet): the graft-fleet
  scaling row — the SAME trace replayed through a FleetRouter over
  SERVE_REPLICAS subprocess workers (fleet/worker.py, compile off the
  clock), reporting aggregate goodput + TTFT p99 so 1/2/4-replica runs
  show the scaling claim (PERF.md §PR17). Fleet is subprocess-only and
  must be the sole mode in the run.
     SERVE_REPLICAS=2               fleet mode: worker process count
     SERVE_TICK_MS=0                fleet mode: emulated per-tick device
                                    time per replica (FLEET_TICK_SLEEP_MS)
                                    — a 1-core CPU rig cannot overlap N
                                    replicas' compute, so the scaling row
                                    runs in the device-bound regime a
                                    real per-replica accelerator gives
     SERVE_TELEMETRY=0              per-tick spans + serve events to a
                                    graft-trace JSONL run dir (drift
                                    summary rides the continuous row)
     SERVE_TELEMETRY_DIR=/tmp/ds_tpu_serve_telemetry
     SERVE_SEED=0
NEVER wrap in `timeout` — clean-exit only (PERF.md wedge lessons).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # bench_core

import numpy as np

MODEL = os.environ.get("SERVE_MODEL", "350m")
MODES = os.environ.get("SERVE_MODE", "both")
QPS = float(os.environ.get("SERVE_QPS", "4.0"))
REQUESTS = int(os.environ.get("SERVE_REQUESTS", "32"))
PROMPT = int(os.environ.get("SERVE_PROMPT", "64"))
NEW = int(os.environ.get("SERVE_NEW", "32"))
LONG_EVERY = int(os.environ.get("SERVE_LONG_EVERY", "0"))
NEW_JITTER = os.environ.get("SERVE_NEW_JITTER", "0") == "1"
SLOTS = int(os.environ.get("SERVE_SLOTS", "8"))
CHUNK = int(os.environ.get("SERVE_CHUNK", "16"))
SPEC = os.environ.get("SERVE_SPEC", "0") == "1"
SPEC_K = int(os.environ.get("SERVE_SPEC_K", "4"))
POOL_TOKENS = int(os.environ.get("SERVE_POOL_TOKENS", "0"))
POOL_BYTES = int(os.environ.get("SERVE_POOL_BYTES", "0"))
WQ = os.environ.get("SERVE_WQ", "fp")
KV_QUANT = os.environ.get("SERVE_KV_QUANT", "1") == "1"
TELEMETRY = os.environ.get("SERVE_TELEMETRY", "0") == "1"
SEED = int(os.environ.get("SERVE_SEED", "0"))
SHARED_PREFIX = int(os.environ.get("SERVE_SHARED_PREFIX", "0"))
REPLICAS = int(os.environ.get("SERVE_REPLICAS", "2"))
TICK_MS = float(os.environ.get("SERVE_TICK_MS", "0"))


def build_engine(n_positions):
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config(MODEL, n_positions=n_positions, dtype=None)
    model = GPT2LMHeadModel(cfg)
    engine = deepspeed_tpu.init_inference(model, replace_with_kernel_inject=True,
                                          max_out_tokens=n_positions)
    return engine, cfg


def build_drafter(engine, cfg, n_positions):
    """The speculation drafter: a layer-reduced KD student seeded from the
    target's own layers (``compression.compress.student_initialization``)
    — the in-tree half the ISSUE names; a trained student drops in the
    same way."""
    import jax
    import flax.linen as nn
    from deepspeed_tpu.compression.compress import student_initialization
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    n_student = max(1, cfg.n_layer // 2)
    # evenly spaced teacher layers seed the student (standard KD recipe)
    teacher_layers = [int(round(i * (cfg.n_layer - 1) / max(n_student - 1, 1)))
                      for i in range(n_student)]
    dcfg = get_gpt2_config(MODEL, n_positions=n_positions, dtype=None,
                           n_layer=n_student)
    drafter = GPT2LMHeadModel(dcfg)
    d_init = nn.meta.unbox(drafter.init(jax.random.PRNGKey(1),
                                        np.zeros((1, 8), np.int32))["params"])
    d_params = student_initialization(
        d_init, jax.device_get(nn.meta.unbox(engine.params)),
        {"compression_training": {"layer_reduction": {
            "enabled": True, "module_name_prefix": "h",
            "teacher_layer": teacher_layers,
            "other_module_name": ["wte", "wpe", "ln_f"]}}})
    return drafter, d_params, teacher_layers


def poisson_trace(rng, vocab):
    """[(arrival_offset_s, prompt, max_new)] — one trace shared by every
    mode so offered load is identical across the comparison."""
    gaps = rng.exponential(1.0 / QPS, REQUESTS)
    arrivals = np.cumsum(gaps)
    trace = []
    for i in range(REQUESTS):
        p = PROMPT * 4 if LONG_EVERY and (i + 1) % LONG_EVERY == 0 else PROMPT
        prompt = rng.integers(0, vocab, (p,)).astype(np.int32)
        n = int(rng.integers(max(NEW // 4, 1), NEW + 1)) if NEW_JITTER else NEW
        trace.append((float(arrivals[i]), prompt, n))
    return trace


def shared_prefix_trace(rng, vocab):
    """The graft-prefix-cache workload family: ``SERVE_SHARED_PREFIX``
    template prefixes (each 3/4 of SERVE_PROMPT tokens, drawn once up
    front), each request = a uniformly drawn template + a unique random
    suffix, arrivals Poisson at SERVE_QPS. Everything is drawn from the
    seeded ``rng``, so cache-on and cache-off arms replay the IDENTICAL
    trace — the A/B's whole premise. Template choice is random rather
    than round-robin: a cyclic assignment resonates with alternating
    least-loaded dispatch (period N divisible by the replica count
    partitions templates perfectly by accident), which would make the
    affinity-vs-least-loaded control meaningless."""
    gaps = rng.exponential(1.0 / QPS, REQUESTS)
    arrivals = np.cumsum(gaps)
    shared = max((PROMPT * 3) // 4, 1)
    templates = [rng.integers(0, vocab, (shared,)).astype(np.int32)
                 for _ in range(SHARED_PREFIX)]
    trace = []
    for i in range(REQUESTS):
        suffix = rng.integers(0, vocab, (PROMPT - shared,)).astype(np.int32)
        t = int(rng.integers(0, SHARED_PREFIX))
        prompt = np.concatenate([templates[t], suffix])
        n = int(rng.integers(max(NEW // 4, 1), NEW + 1)) if NEW_JITTER else NEW
        trace.append((float(arrivals[i]), prompt, n))
    return trace


def _lat_row(hist):
    if hist is None or (hasattr(hist, "count") and not hist.count):
        return None
    snap = hist.snapshot() if hasattr(hist, "snapshot") else hist
    return {k: round(v, 4) for k, v in snap.items()
            if k in ("p50", "p90", "p99", "min", "max", "mean")}


def serve_evidence(engine, slots, wq="fp", kv_quant=False):
    """Static lint + cost evidence for the decode program this run serves
    (the perf-ladder contract: a banked latency row must prove its
    program passes the same gates CI enforces). ``wq``/``kv_quant`` price
    the QUANTIZED program when a quantized row banks evidence."""
    try:
        import jax
        import jax.numpy as jnp
        from deepspeed_tpu import analysis
        from deepspeed_tpu.analysis.memory import estimate_memory
        from deepspeed_tpu.analysis.program import ProgramInfo
        from deepspeed_tpu.inference.serving import make_slot_cache, resolve_kv_write
        from deepspeed_tpu.inference.serving.programs import (build_decode_step,
                                                              make_apply_fn)

        slots = engine._pow2_bucket(slots)  # price the program actually served
        module, params = engine.module, engine.params
        if wq != "fp":
            from deepspeed_tpu.inference.serving.scheduler import _quant_view
            module, params = _quant_view(module, params, wq, 64)
        cache = make_slot_cache(module, slots, kv_quant=kv_quant)
        decode = build_decode_step(make_apply_fn(module, engine._mparams),
                                   False, 1.0, 0, 1.0)
        tokens = jnp.zeros((slots,), jnp.int32)
        jaxpr = jax.make_jaxpr(decode)(params, cache, tokens)
        info = ProgramInfo(name="serve_decode", jaxpr=jaxpr, kind="serve_decode")
        findings, _ = analysis.run_program_rules(info)
        mem = estimate_memory(info)
        mode, src = resolve_kv_write(None)
        return {"serve_lint": analysis.summarize(findings),
                "serve_cost_peak_bytes": mem.peak_bytes,
                "serve_cost_transient_bytes": mem.peak_transient_bytes,
                "serve_kv_write": mode, "serve_kv_write_source": src,
                "serve_weight_dtype": wq, "serve_kv_quant": kv_quant}
    except Exception as e:  # evidence must never kill a run
        return {"serve_evidence_error": f"{type(e).__name__}: {str(e)[:120]}"}


def run_continuous(engine, cfg, trace, drafter=None, telemetry=None,
                   wq=None, kv_quant=None, pool_bytes=None, label="continuous",
                   collect_outputs=False, prefix_cache=None):
    from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                                 Request, ServingConfig)

    n_positions = cfg.n_positions
    scfg = ServingConfig(
        slots=SLOTS, page_size=16,
        kv_pool_tokens=POOL_TOKENS or None,
        kv_pool_bytes=(POOL_BYTES or None) if pool_bytes is None else pool_bytes,
        # explicit wq (the quant_ab arms) is passed verbatim as the config
        # layer; env-driven runs map SERVE_WQ=fp to None. DS_SERVE_WQ
        # still outranks either — the drift seam is deliberate, and lint
        # (not the bench) is what catches a leaked env
        weight_dtype=(None if WQ == "fp" else WQ) if wq is None else wq,
        kv_quant=KV_QUANT if kv_quant is None else kv_quant,
        # None = the DS_SERVE_PREFIX_CACHE/config resolution (default on);
        # the prefix_ab arms pin "on"/"off" explicitly
        prefix_cache=prefix_cache,
        prefill_chunk=CHUNK if CHUNK > 0 else n_positions,
        speculation={"enabled": drafter is not None, "k": SPEC_K})
    sched = ContinuousBatchingScheduler(engine, scfg, drafter=drafter,
                                        telemetry=telemetry)
    # compile every serving program off the clock — including rare-path
    # ones a warm request can't reliably reach, like the drafter's
    # full-k refeed verify (latency-under-load must not charge a
    # mid-serve request for XLA compile time)
    sched.warmup()

    t0 = time.monotonic()
    i = 0
    reqs = []
    while i < len(trace) or sched.in_flight or len(sched.queue):
        now = time.monotonic() - t0
        while i < len(trace) and trace[i][0] <= now:
            _, prompt, new = trace[i]
            r = Request(prompt=prompt, max_new_tokens=new,
                        arrival_time=t0 + trace[i][0])
            sched.submit(r)
            reqs.append(r)
            i += 1
        if sched.in_flight or len(sched.queue):
            sched.step()
        elif i < len(trace):
            time.sleep(min(max(trace[i][0] - now, 0.0), 0.05))
    wall = time.monotonic() - t0
    stats = sched.stats()
    row = {
        "mode": label, "wall_s": round(wall, 3),
        "finished": stats["finished"], "refused": stats["refused"],
        "goodput_tok_s": round(stats["generated_tokens"] / wall, 1),
        "ttft": _lat_row(stats["ttft"]), "per_token": _lat_row(stats["per_token"]),
        "ticks": stats["ticks"], "pool": stats["pool"],
        "weight_dtype": stats["weight_dtype"],
        "weight_dtype_source": stats["weight_dtype_source"],
        "kv_quant": stats["kv_quant"],
        "prefix_cache": stats["prefix_cache"],
        "prefix_cache_source": stats["prefix_cache_source"],
        "cached_prefix_tokens": stats["cached_prefix_tokens"],
        "prefix_hit_rate": stats["pool"].get("prefix_hit_rate"),
        "chunked_prefill": CHUNK > 0, "prefill_chunk": CHUNK or n_positions,
        "slots": sched.slots,
    }
    if collect_outputs:
        row["_outputs"] = [list(r.output) for r in reqs]
    if drafter is not None:
        row["speculation"] = {"k": SPEC_K,
                              "drafted": stats["drafted"],
                              "accepted": stats["accepted"],
                              "acceptance_rate": round(stats["acceptance_rate"], 3)
                              if stats["acceptance_rate"] is not None else None}
    if telemetry is not None and telemetry.enabled:
        row["telemetry"] = telemetry.drift_summary()
    return row


def _token_match(quant_outputs, fp_outputs):
    """Token-level greedy match of the quantized arm against fp — the
    speculative-acceptance metric applied across serving stacks: per
    request, the longest common prefix counts as accepted (a diverged
    token invalidates its suffix exactly as a rejected draft would)."""
    accepted = total = 0
    exact = 0
    for q, f in zip(quant_outputs, fp_outputs):
        n = 0
        for a, b in zip(q, f):
            if a != b:
                break
            n += 1
        accepted += n
        total += max(len(f), len(q))
        exact += int(q == f and len(q) > 0)
    return {"token_match_rate": round(accepted / max(total, 1), 4),
            "exact_output_requests": exact, "requests": len(fp_outputs)}


def quant_ab(engine, cfg, trace, header, drafter=None):
    """The graft-quant-serve A/B (PERF.md §PR16): the same trace served by
    the fp stack and by the int8-weight + int8-KV stack under the SAME KV
    byte budget (SERVE_POOL_BYTES; defaults to the fp pool's full-context
    footprint HALVED, so the budget is genuinely scarce for fp). Reports
    blocks-per-GB, goodput ratio, and the token-level greedy match."""
    budget = POOL_BYTES
    if not budget:
        fp_probe = _probe_kv_bytes_per_token(engine, cfg)
        budget = int(SLOTS * cfg.n_positions * fp_probe) // 2
        print(f"# quant_ab: SERVE_POOL_BYTES unset, using half the fp "
              f"full-context footprint = {budget} bytes", flush=True)
    wq = WQ if WQ != "fp" else "int8"
    arms = {}
    for label, arm_wq, kvq in (("fp", "fp", False), ("quant", wq, True)):
        row = run_continuous(engine, cfg, trace, drafter=drafter, wq=arm_wq,
                             kv_quant=kvq, pool_bytes=budget,
                             label=f"quant_ab:{label}", collect_outputs=True)
        row.update(serve_evidence(engine, SLOTS, wq=arm_wq, kv_quant=kvq))
        arms[label] = row
        printable = dict(header, **{k: v for k, v in row.items()
                                    if not k.startswith("_")})
        print(json.dumps(printable), flush=True)
    fp_row, q_row = arms["fp"], arms["quant"]
    comparison = {
        "comparison": "quant_vs_fp", "qps": QPS, "weight_dtype": wq,
        "kv_pool_bytes": budget,
        "kv_blocks_fp": fp_row["pool"]["num_blocks"],
        "kv_blocks_quant": q_row["pool"]["num_blocks"],
        "kv_blocks_per_gb_fp": fp_row["pool"]["kv_blocks_per_gb"],
        "kv_blocks_per_gb_quant": q_row["pool"]["kv_blocks_per_gb"],
        "goodput_fp_tok_s": fp_row["goodput_tok_s"],
        "goodput_quant_tok_s": q_row["goodput_tok_s"],
        "goodput_ratio": round(q_row["goodput_tok_s"]
                               / max(fp_row["goodput_tok_s"], 1e-9), 3),
        "greedy_match": _token_match(q_row["_outputs"], fp_row["_outputs"]),
        "quant_beats_fp_goodput":
            q_row["goodput_tok_s"] > fp_row["goodput_tok_s"],
        "quant_more_blocks_per_gb":
            q_row["pool"]["kv_blocks_per_gb"] > fp_row["pool"]["kv_blocks_per_gb"],
    }
    print(json.dumps(comparison), flush=True)
    return comparison


def prefix_ab(engine, cfg, trace, header, drafter=None):
    """The graft-prefix-cache A/B (PERF.md §PR19): the same trace served
    twice — prefix cache OFF then ON — with IDENTICAL pool sizing (same
    SERVE_POOL_TOKENS/SERVE_POOL_BYTES, asserted on the pool the
    scheduler actually built). Reports goodput ratio, per-arm TTFT p99,
    hit rate / cached-tokens / cached-blocks evidence, and the
    token-level greedy match of the cached arm against the uncached one.
    The match must be EXACT: a cache hit restores the same KV bytes
    prefill would have written, so any divergence is a correctness bug,
    not a tolerance."""
    arms = {}
    for label in ("off", "on"):
        row = run_continuous(engine, cfg, trace, drafter=drafter,
                             prefix_cache=label, label=f"prefix_ab:{label}",
                             collect_outputs=True)
        row.update(serve_evidence(engine, SLOTS, wq=row["weight_dtype"],
                                  kv_quant=row["kv_quant"]))
        arms[label] = row
        printable = dict(header, **{k: v for k, v in row.items()
                                    if not k.startswith("_")})
        print(json.dumps(printable), flush=True)
    off_row, on_row = arms["off"], arms["on"]
    comparison = {
        "comparison": "prefix_cache_on_vs_off", "qps": QPS,
        "shared_prefix_templates": SHARED_PREFIX or None,
        "pool_blocks_off": off_row["pool"]["num_blocks"],
        "pool_blocks_on": on_row["pool"]["num_blocks"],
        "pool_blocks_equal":
            off_row["pool"]["num_blocks"] == on_row["pool"]["num_blocks"],
        "prefix_hit_rate": on_row["prefix_hit_rate"],
        "cached_prefix_tokens": on_row["cached_prefix_tokens"],
        "cached_blocks_final": on_row["pool"]["cached_blocks"],
        "published_blocks": on_row["pool"]["published_blocks"],
        "goodput_off_tok_s": off_row["goodput_tok_s"],
        "goodput_on_tok_s": on_row["goodput_tok_s"],
        "goodput_ratio": round(on_row["goodput_tok_s"]
                               / max(off_row["goodput_tok_s"], 1e-9), 3),
        "ttft_p99_off": (off_row["ttft"] or {}).get("p99"),
        "ttft_p99_on": (on_row["ttft"] or {}).get("p99"),
        "ttft_p99_improved":
            (on_row["ttft"] or {}).get("p99") is not None
            and (off_row["ttft"] or {}).get("p99") is not None
            and on_row["ttft"]["p99"] < off_row["ttft"]["p99"],
        "greedy_match": _token_match(on_row["_outputs"], off_row["_outputs"]),
        "cache_on_beats_off_goodput":
            on_row["goodput_tok_s"] > off_row["goodput_tok_s"],
    }
    print(json.dumps(comparison), flush=True)
    return comparison


def _probe_kv_bytes_per_token(engine, cfg):
    """The fp cache's per-token KV footprint, measured the same way the
    scheduler's byte-budget sizing measures it."""
    from deepspeed_tpu.inference.serving import ServingConfig
    from deepspeed_tpu.inference.serving.scheduler import ContinuousBatchingScheduler
    probe = ContinuousBatchingScheduler(
        engine, ServingConfig(slots=SLOTS, kv_quant=False))
    return probe._kv_bytes_per_token()


def run_static(engine, cfg, trace):
    """The pre-PR-14 baseline: accumulate arrivals into fixed batches of
    ``SLOTS`` and run offline ``engine.generate`` per batch. Every token
    of a request becomes available only when its whole batch finishes —
    which is exactly the latency story continuous batching replaces."""
    from deepspeed_tpu.runtime.telemetry import Histogram

    # warm the generate programs off the clock at the REAL batch bucket
    # (generate caches per pow2 bucket: a batch-1 warm would leave the
    # timed flushes paying the SLOTS-bucket compile — same courtesy as
    # continuous warming its own fixed-shape programs)
    engine.generate(np.repeat(trace[0][1][None, :], SLOTS, axis=0),
                    max_new_tokens=2)

    ttft_h, tok_h = Histogram(), Histogram()
    t0 = time.monotonic()
    i, batch, finished, tokens_out = 0, [], 0, 0
    while i < len(trace) or batch:
        now = time.monotonic() - t0
        while i < len(trace) and trace[i][0] <= now:
            batch.append(trace[i])
            i += 1
        flush = len(batch) >= SLOTS or (batch and i >= len(trace))
        if flush:
            part, batch = batch[:SLOTS], batch[SLOTS:]
            prompts = np.stack([p for _, p, _ in part])
            new = max(n for _, _, n in part)
            out = np.asarray(engine.generate(prompts, max_new_tokens=new))
            done = time.monotonic() - t0
            per_tok = (done - now) / max(new, 1)
            for arr, _, n in part:
                ttft_h.record(done - arr)   # first token only at batch end
                for _ in range(n - 1):
                    tok_h.record(per_tok)
                finished += 1
                tokens_out += n
            del out
        elif i < len(trace):
            time.sleep(min(max(trace[i][0] - now, 0.0), 0.05))
    wall = time.monotonic() - t0
    return {"mode": "static", "wall_s": round(wall, 3), "finished": finished,
            "refused": 0, "goodput_tok_s": round(tokens_out / wall, 1),
            "ttft": _lat_row(ttft_h), "per_token": _lat_row(tok_h),
            "batch": SLOTS}


def run_fleet(cfg, trace, n_positions):
    """The graft-fleet scaling row: replay the shared Poisson trace
    through a FleetRouter over ``REPLICAS`` real worker subprocesses.
    Engine build + warmup happen in each worker BEFORE the clock starts
    (``wait_ready``), so the timed window measures serving, not XLA.
    TTFT is the per-request value each worker's scheduler measured
    (dispatch is immediate, so worker admission ≈ router arrival)."""
    import shutil
    import tempfile

    from deepspeed_tpu.inference.fleet import FleetRouter, SubprocessReplica
    from deepspeed_tpu.runtime.telemetry import Histogram

    workdir = tempfile.mkdtemp(prefix="ds_tpu_fleet_")
    env = {"FLEET_MODEL": MODEL, "FLEET_POSITIONS": str(n_positions),
           "FLEET_SLOTS": str(SLOTS),
           "FLEET_CHUNK": str(CHUNK if CHUNK > 0 else n_positions),
           "FLEET_KV_QUANT": "1" if KV_QUANT else "0"}
    if POOL_TOKENS:
        env["FLEET_POOL_TOKENS"] = str(POOL_TOKENS)
    if TICK_MS:
        env["FLEET_TICK_SLEEP_MS"] = str(TICK_MS)
    if TELEMETRY:
        env["FLEET_TELEMETRY_DIR"] = os.environ.get(
            "SERVE_TELEMETRY_DIR", "/tmp/ds_tpu_serve_telemetry")
    # prefix-affinity dispatch A/B toggle (FLEET_AFFINITY=0 = pure
    # least-loaded): the serve_prefix_fleet_* perf-ladder rungs compare
    # the two on the same shared-prefix trace
    affinity = os.environ.get("FLEET_AFFINITY", "1") == "1"
    router = FleetRouter(heartbeat_timeout=120.0, affinity=affinity)
    replicas = [SubprocessReplica(f"w{i}", os.path.join(workdir, f"w{i}"),
                                  env=env)
                for i in range(REPLICAS)]
    try:
        for r in replicas:
            r.wait_ready(timeout=600.0)
            router.add_replica(r.name, r)
        print(f"# fleet: {REPLICAS} replica(s) ready, replaying trace",
              flush=True)
        t0 = time.monotonic()
        i = 0
        while i < len(trace) or router.pending:
            now = time.monotonic() - t0
            while i < len(trace) and trace[i][0] <= now:
                _, prompt, new = trace[i]
                router.submit(prompt, new)
                i += 1
            router.poll()
            if not router.pending and i < len(trace):
                time.sleep(min(max(trace[i][0] - now, 0.0), 0.05))
            else:
                time.sleep(0.005)
        wall = time.monotonic() - t0
        ttft_h = Histogram()
        tokens_out = 0
        for rec in router.completed.values():
            st = rec.get("stats") or {}
            if st.get("ttft") is not None:
                ttft_h.record(st["ttft"])
            tokens_out += st.get("new_tokens") or len(rec.get("output") or [])
        rstats = router.stats()
        return {
            "mode": f"fleet:{REPLICAS}", "replicas": REPLICAS,
            "wall_s": round(wall, 3),
            "finished": rstats["completed"], "failed": rstats["failed"],
            "duplicate_completions": rstats["duplicate_completions"],
            "readmitted": rstats["readmitted"],
            "completed_by": rstats["completed_by"],
            "affinity": rstats["affinity"],
            "affinity_hits": rstats["affinity_hits"],
            "affinity_overruled": rstats["affinity_overruled"],
            "ticks_by": {r.name: r.ticks_seen for r in replicas},
            "goodput_tok_s": round(tokens_out / wall, 1),
            "ttft": _lat_row(ttft_h),
            "slots_per_replica": SLOTS, "kv_quant": KV_QUANT,
            "chunked_prefill": CHUNK > 0,
            "prefill_chunk": CHUNK or n_positions,
            "emulated_tick_ms": TICK_MS or None,
        }
    finally:
        for r in replicas:
            r.close()
        shutil.rmtree(workdir, ignore_errors=True)


def main():
    import jax

    from bench_core import enable_compile_cache

    # knob incompatibilities are knowable from env alone — fail them
    # BEFORE paying minutes of engine build + compile + continuous replay
    modes = ["continuous", "static"] if MODES == "both" else MODES.split(",")
    if "--fleet" in sys.argv:
        modes = ["fleet"]
    if "--prefix-ab" in sys.argv:
        modes = ["prefix_ab"]
    unknown = [m for m in modes
               if m not in ("continuous", "static", "quant_ab", "prefix_ab",
                            "fleet")]
    if unknown:
        raise SystemExit(f"unknown SERVE_MODE entry {unknown[0]!r}")
    if "fleet" in modes and modes != ["fleet"]:
        raise SystemExit("fleet mode runs alone (workers own the engines; "
                         "there is no parent engine to share with other modes)")
    if REPLICAS < 1:
        raise SystemExit(f"SERVE_REPLICAS must be >= 1, got {REPLICAS}")
    if WQ not in ("fp", "int8", "int4"):
        raise SystemExit(f"SERVE_WQ must be fp|int8|int4, got {WQ!r}")
    if LONG_EVERY and "static" in modes:
        raise SystemExit(
            "static mode cannot batch ragged prompts (SERVE_LONG_EVERY): "
            "the chunked-prefill A/B is continuous-only — use "
            "SERVE_MODE=continuous")
    if SPEC and "static" in modes:
        print("# static mode ignores SERVE_SPEC (no speculation offline)",
              flush=True)

    enable_compile_cache()
    n_positions = max((PROMPT * 4 if LONG_EVERY else PROMPT) + NEW + 1, 128)
    if modes == ["fleet"]:
        # workers build their own engines; the parent only needs the
        # vocab size to synthesize the trace
        from deepspeed_tpu.models import get_gpt2_config
        engine, cfg = None, get_gpt2_config(MODEL, n_positions=n_positions,
                                            dtype=None)
    else:
        engine, cfg = build_engine(n_positions)
    rng = np.random.default_rng(SEED)
    trace = (shared_prefix_trace(rng, cfg.vocab_size) if SHARED_PREFIX
             else poisson_trace(rng, cfg.vocab_size))

    drafter = None
    if SPEC and ("continuous" in modes or "quant_ab" in modes):
        d_module, d_params, teacher_layers = build_drafter(engine, cfg, n_positions)
        drafter = (d_module, d_params)
        print(f"# drafter: {d_module.config.n_layer}-layer KD student seeded "
              f"from teacher layers {teacher_layers}", flush=True)

    telemetry = None
    if TELEMETRY:
        from deepspeed_tpu.runtime.config import TelemetryConfig
        from deepspeed_tpu.runtime.telemetry import RuntimeTelemetry
        telemetry = RuntimeTelemetry(TelemetryConfig(
            enabled=True,
            output_path=os.environ.get("SERVE_TELEMETRY_DIR",
                                       "/tmp/ds_tpu_serve_telemetry"),
            job_name=f"serve_{MODEL}_qps{QPS}"))
        from deepspeed_tpu.inference.serving import resolve_prefix_cache
        # graft-calibrate separation markers (same contract as the fleet
        # worker's header): the field's presence keys collect_samples'
        # mixed-run refusal for serve-scope samples
        telemetry.write_run_header({"bench": "serve_bench", "model": MODEL,
                                    "qps": QPS, "slots": SLOTS,
                                    "prefix_cache": resolve_prefix_cache(None)[0],
                                    "cached_prefix_tokens": 0})

    rows = {}
    header = {"model": MODEL, "qps": QPS, "requests": REQUESTS, "prompt": PROMPT,
              "new": NEW, "new_jitter": NEW_JITTER, "long_every": LONG_EVERY,
              "slots": SLOTS, "backend": jax.default_backend(), "seed": SEED,
              "shared_prefix": SHARED_PREFIX or None}
    for mode in modes:
        if mode == "continuous":
            row = run_continuous(engine, cfg, trace, drafter=drafter,
                                 telemetry=telemetry)
            row.update(serve_evidence(engine, SLOTS,
                                      wq=row["weight_dtype"],
                                      kv_quant=row["kv_quant"]))
        elif mode == "quant_ab":
            quant_ab(engine, cfg, trace, header, drafter=drafter)
            continue
        elif mode == "prefix_ab":
            prefix_ab(engine, cfg, trace, header, drafter=drafter)
            continue
        elif mode == "fleet":
            row = run_fleet(cfg, trace, n_positions)
        else:
            row = run_static(engine, cfg, trace)
        rows[mode] = dict(header, **row)
        print(json.dumps(rows[mode]), flush=True)
    if telemetry is not None:
        telemetry.close()
    if "continuous" in rows and "static" in rows:
        c, s = rows["continuous"], rows["static"]
        comparison = {
            "comparison": "continuous_vs_static", "qps": QPS,
            "goodput_ratio": round(c["goodput_tok_s"] / max(s["goodput_tok_s"], 1e-9), 3),
            "ttft_p99_ratio": (round(c["ttft"]["p99"] / s["ttft"]["p99"], 3)
                               if c.get("ttft") and s.get("ttft") else None),
            "continuous_beats_static_goodput":
                c["goodput_tok_s"] > s["goodput_tok_s"],
        }
        print(json.dumps(comparison), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
