"""Serving decode throughput on the real chip — the inference-side
companion to bench.py (the reference's inference benchmarks live in
DeepSpeedExamples; its headline is fused-kernel decode speed).

Measures decode tokens/s by DIFFERENCING: each round times generate()
at ``NEW`` and at ``2*NEW`` new tokens with the same prompt shape — the
prefill cost cancels in the difference, so the decode rate is isolated
from the per-dispatch chunked prefill (whose timing the tunnel's dedupe
cache can flatter, PERF.md session 3; the decode while_loop itself
chains state token-by-token). End-to-end rate reports alongside.

Run: python tools/serve_bench.py    (background it; poll stdout)
Env: SERVE_MODEL=350m SERVE_BATCH=8 SERVE_PROMPT=128 SERVE_NEW=128
     SERVE_ROUNDS=3
NEVER wrap in `timeout` — clean-exit only (PERF.md wedge lessons).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # bench_core

import numpy as np

MODEL = os.environ.get("SERVE_MODEL", "350m")
BATCH = int(os.environ.get("SERVE_BATCH", "8"))
PROMPT = int(os.environ.get("SERVE_PROMPT", "128"))
NEW = int(os.environ.get("SERVE_NEW", "128"))
ROUNDS = int(os.environ.get("SERVE_ROUNDS", "3"))


def main():
    import jax

    from bench_core import enable_compile_cache

    enable_compile_cache()
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config(MODEL, n_positions=PROMPT + 2 * NEW, dtype=None)
    model = GPT2LMHeadModel(cfg)
    engine = deepspeed_tpu.init_inference(model, dtype="bf16",
                                          replace_with_kernel_inject=True,
                                          max_out_tokens=PROMPT + 2 * NEW)
    rng = np.random.default_rng(0)

    def run(new_tokens):
        prompts = rng.integers(0, cfg.vocab_size, (BATCH, PROMPT)).astype(np.int32)
        t0 = time.time()
        out = np.asarray(engine.generate(prompts, max_new_tokens=new_tokens))
        dt = time.time() - t0
        assert out.shape == (BATCH, PROMPT + new_tokens)
        return dt

    t0 = time.time()
    run(NEW)
    run(2 * NEW)  # compile both programs
    compile_s = time.time() - t0

    # latency distributions ride the telemetry Histogram (fixed buckets,
    # mergeable) — the same type the continuous-batching latency-under-load
    # successor (ROADMAP 1) will aggregate across request streams
    from deepspeed_tpu.runtime.telemetry import Histogram
    lat_short, lat_long = Histogram(), Histogram()
    short, long_ = [], []
    for r in range(ROUNDS):
        short.append(run(NEW))
        lat_short.record(short[-1])
        long_.append(run(2 * NEW))
        lat_long.record(long_[-1])
    d_short, d_long = float(np.median(short)), float(np.median(long_))
    # prefill cancels in the difference; decode rate from the extra NEW tokens
    decode_dt = max(d_long - d_short, 1e-9)
    decode_tok_s = BATCH * NEW / decode_dt
    e2e_tok_s = BATCH * NEW / d_short
    print(json.dumps({
        "model": MODEL, "batch": BATCH, "prompt": PROMPT, "new": NEW,
        "decode_tokens_per_s": round(decode_tok_s, 1),
        "decode_ms_per_token": round(decode_dt / NEW * 1e3, 2),
        "e2e_tokens_per_s_incl_prefill": round(e2e_tok_s, 1),
        "round_s_short": [round(t, 3) for t in short],
        "round_s_long": [round(t, 3) for t in long_],
        "latency_short": {k: round(v, 4) for k, v in lat_short.snapshot().items()
                          if k in ("p50", "p90", "p99", "min", "max", "mean")},
        "latency_long": {k: round(v, 4) for k, v in lat_long.snapshot().items()
                         if k in ("p50", "p90", "p99", "min", "max", "mean")},
        "compile_s": round(compile_s, 1),
        "backend": jax.default_backend(),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
