"""trace_report: turn a graft-trace telemetry JSONL into human/tool views.

Two modes over the run log ``runtime/telemetry`` writes:

* default — export the step-span timeline as **Chrome trace-event JSON**
  (the ``chrome://tracing`` / Perfetto "JSON Array with metadata" format:
  ``{"traceEvents": [...]}`` of complete ``"ph": "X"`` events). Span
  nesting falls out of timestamp containment on one tid; ``step_window``
  aggregates ride along as counter (``"ph": "C"``) series so achieved
  step time is visible next to the phases.
* ``--drift`` — summarize the predicted-vs-measured loop: the run
  header's static price (flops_proxy, liveness peak/transient bytes)
  against each window's measured median step time and memory peaks,
  printed as a table plus one JSON summary line, AND written as a
  machine-readable sidecar (default ``<run_dir>/drift.json``, ``--out``
  overrides, ``-`` suppresses) — the per-window predicted/measured/ratio
  rows ``tools/graft_calibrate.py`` fits calibration coefficients from.
  This is the chip-window view that banks *model error*, not just
  milliseconds.

This tool only READS json — no jax import, safe anywhere (including
while a run is still writing; torn tail lines are skipped).

Usage:
  python tools/trace_report.py <run_dir_or_jsonl> [--out trace.json]
  python tools/trace_report.py <run_dir_or_jsonl> --drift
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from deepspeed_tpu.runtime.telemetry.core import TELEMETRY_FILE, drift_ratios  # noqa: E402
from deepspeed_tpu.runtime.telemetry.sink import iter_events  # noqa: E402


def resolve_jsonl(path: str) -> str:
    """Accept the run dir or the jsonl file itself."""
    if os.path.isdir(path):
        candidate = os.path.join(path, TELEMETRY_FILE)
        if not os.path.exists(candidate):
            raise FileNotFoundError(f"no {TELEMETRY_FILE} under {path}")
        return candidate
    return path


def chrome_trace(events) -> dict:
    """Chrome trace-event JSON from the run's span + window events."""
    trace = []
    pid = 0
    run = {}
    for rec in events:
        kind = rec.get("event")
        if kind == "run_start":
            run = rec.get("run") or {}
            pid = run.get("pid", 0) or 0
            trace.append({"name": "process_name", "ph": "M", "pid": pid,
                          "args": {"name": f"deepspeed_tpu {run.get('model', '')} "
                                           f"[{run.get('config_sig', '')}]".strip()}})
            trace.append({"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
                          "args": {"name": "step spans"}})
        elif kind == "spans":
            for s in rec.get("spans", ()):
                trace.append({"name": s.get("name", "?"), "ph": "X", "pid": pid,
                              "tid": 1,
                              "ts": float(s.get("ts", 0.0)) * 1e6,
                              "dur": float(s.get("dur_s", 0.0)) * 1e6,
                              "args": {"path": s.get("path", ""),
                                       "depth": s.get("depth", 0)}})
        elif kind == "step_window":
            step_phase = (rec.get("phases") or {}).get("step") or {}
            p50 = step_phase.get("p50")
            if p50 is not None:
                trace.append({"name": "step_p50_ms", "ph": "C", "pid": pid, "tid": 0,
                              "ts": float(rec.get("t", 0.0)) * 1e6,
                              "args": {"ms": p50 * 1e3}})
        elif kind in ("checkpoint", "xla_trace", "preempt_checkpoint"):
            trace.append({"name": kind, "ph": "i", "pid": pid, "tid": 1, "s": "g",
                          "ts": float(rec.get("t", 0.0)) * 1e6,
                          "args": {k: v for k, v in rec.items()
                                   if k not in ("event", "t")}})
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"run": run}}


def drift_report(events) -> dict:
    """Windows + overall summary of predicted-vs-measured."""
    price, run, windows = None, {}, []
    for rec in events:
        if rec.get("event") == "run_start":
            run = rec.get("run") or {}
            price = rec.get("static_price")
        elif rec.get("event") == "drift":
            windows.append(rec)
    # overall: time-weighted across windows (median of window medians is
    # fine at this granularity; windows are equal step counts by cadence)
    meds = [w["median_step_s"] for w in windows if w.get("median_step_s")]
    med = sorted(meds)[len(meds) // 2] if meds else None
    measured = windows[-1].get("measured") if windows else {}
    return {"run": run, "predicted": price, "windows": windows,
            "median_step_s": med,
            "ratios": drift_ratios(price, med, measured)}


def print_drift(report) -> None:
    price = report.get("predicted") or {}
    run = report.get("run") or {}
    print(f"# drift report: model={run.get('model')} config={run.get('config_sig')} "
          f"backend={run.get('backend')}")
    if price.get("error"):
        # pricing failed at header time (the engine degrades to an
        # {"error": ...} stamp) — report that instead of crashing the
        # one tool meant to inspect such runs
        print(f"# predicted: unavailable ({price['error']})")
    elif price:
        print(f"# predicted: flops_proxy={_count(price.get('flops_proxy'))} "
              f"peak={_mib(price.get('peak_bytes'))} "
              f"transient={_mib(price.get('peak_transient_bytes'))} "
              f"wire={_mib(price.get('bytes_moved'))}")
    hdr = f"{'step':>8} {'steps':>6} {'med_ms':>10} {'TFLOPS':>9}  memory ratios"
    print(hdr)
    for w in report["windows"]:
        med = w.get("median_step_s")
        r = w.get("ratios") or {}
        ratio_bits = " ".join(f"{k}={v:.3f}" for k, v in r.items()
                              if k != "achieved_tflops")
        print(f"{w.get('step', '?'):>8} {w.get('window_steps', '?'):>6} "
              f"{(med or 0) * 1e3:>10.3f} {r.get('achieved_tflops', 0):>9.4f}  "
              f"{ratio_bits}")
    print(json.dumps({"summary": {"median_step_s": report["median_step_s"],
                                  "ratios": report["ratios"]}}))


def _mib(n):
    return f"{n / 2**20:.1f}MiB" if isinstance(n, (int, float)) else "n/a"


def _count(n):
    return f"{n:,}" if isinstance(n, (int, float)) else "n/a"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trace_report", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("path", help="telemetry run dir or telemetry.jsonl")
    ap.add_argument("--out", default=None,
                    help="output path: the Chrome trace JSON (default "
                         "<run_dir>/chrome_trace.json, '-' for stdout) or, "
                         "with --drift, the JSON sidecar (default "
                         "<run_dir>/drift.json, '-' to suppress)")
    ap.add_argument("--drift", action="store_true",
                    help="print the predicted-vs-measured drift table and "
                         "write the machine-readable drift.json sidecar instead")
    args = ap.parse_args(argv)

    jsonl = resolve_jsonl(args.path)
    events = list(iter_events(jsonl))
    if not events:
        print(f"trace_report: no events in {jsonl}", file=sys.stderr)
        return 1

    if args.drift:
        report = drift_report(events)
        print_drift(report)
        # the sidecar keeps the drift rows machine-readable instead of
        # dying in stdout — graft_calibrate consumes it as a fit source
        out = args.out or os.path.join(os.path.dirname(jsonl), "drift.json")
        if out != "-":
            with open(out, "w") as fh:
                json.dump(report, fh, indent=2)
                fh.write("\n")
            print(f"drift sidecar: {out} ({len(report['windows'])} windows)")
        return 0

    trace = chrome_trace(events)
    if not trace["traceEvents"]:
        print(f"trace_report: no span events in {jsonl} (telemetry.span_events "
              f"off, or the run never reached a flush boundary)", file=sys.stderr)
        return 1
    out = args.out or os.path.join(os.path.dirname(jsonl), "chrome_trace.json")
    if out == "-":
        json.dump(trace, sys.stdout)
        print()
    else:
        with open(out, "w") as fh:
            json.dump(trace, fh)
        print(f"chrome trace: {out} ({len(trace['traceEvents'])} events) — "
              f"load in chrome://tracing or ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
