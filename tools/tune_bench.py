"""Autotuner measured-mode validation on real hardware (r4 verdict Weak #6:
the cost ordering had never touched real timings). Runs a measured tune —
stage x micro-batch ladder on the live backend, timings through the fused
``train_batches`` dispatch — and reports every measured candidate plus the
winner, so the ranking can be checked against the banked bench numbers
(350m mb=8 ~ 70 TFLOPS was the hand-found optimum; the tuner should agree
or beat it).

Run: python tools/tune_bench.py        (background; clean-exit; NEVER
     timeout-wrap on the tunnel)
Env: TUNE_MODEL=350m TUNE_SEQ=1024 TUNE_MAX_MBS=16 TUNE_STAGES=0,1
     TUNE_STEPS=6 (timed steps per candidate)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

MODEL = os.environ.get("TUNE_MODEL", "350m")
SEQ = int(os.environ.get("TUNE_SEQ", "1024"))
MAX_MBS = int(os.environ.get("TUNE_MAX_MBS", "16"))
STAGES = [int(s) for s in os.environ.get("TUNE_STAGES", "0,1").split(",")]
STEPS = int(os.environ.get("TUNE_STEPS", "6"))


def main():
    import jax

    from bench_core import enable_compile_cache, flops_per_token_from_cfg

    enable_compile_cache()
    import jax.numpy as jnp

    from deepspeed_tpu.autotuning.autotuner import Autotuner
    from deepspeed_tpu.models import GPT2LMHeadModel, get_gpt2_config

    cfg = get_gpt2_config(MODEL, n_positions=SEQ, remat=True,
                          attention_backend="flash"
                          if jax.default_backend() in ("tpu", "axon") else "xla",
                          dtype=jnp.bfloat16, vocab_size=50304,
                          embed_onehot_grad=True, fused_head_loss_chunk=1024)
    user_config = {
        "train_batch_size": jax.device_count(),  # rescaled per candidate
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "gradient_clipping": 1.0,
        "zero_optimization": {"stage": STAGES[0]},
        "steps_per_print": 10**9,
        "autotuning": {"enabled": True, "measure": True, "top_k": 3,
                       "zero_stages": STAGES,
                       "start_profile_step": 1, "end_profile_step": 1 + STEPS,
                       "max_train_micro_batch_size_per_gpu": MAX_MBS,
                       # default repo-relative dirs are the committed chip
                       # evidence — CI smoke runs redirect to a tmp dir so
                       # they never churn the banked artifacts
                       "results_dir": os.environ.get(
                           "TUNE_RESULTS_DIR", "autotuning_results"),
                       "exps_dir": os.environ.get(
                           "TUNE_EXPS_DIR", "autotuning_exps")},
    }
    rng = np.random.default_rng(0)
    example = {"input_ids": rng.integers(0, cfg.vocab_size,
                                         (jax.device_count(), SEQ)).astype(np.int32)}
    t0 = time.time()
    tuner = Autotuner(model=GPT2LMHeadModel(cfg), config=user_config,
                      example_batch=example)
    best = tuner.tune()
    fpt = flops_per_token_from_cfg(tuner.get_model_num_params() or 0, cfg, SEQ)
    rows = []
    for exp in tuner.records:
        row = {"name": exp.name, "status": exp.status,
               "metric_val": exp.metric_val}
        if exp.measured_step_s:
            tok = exp.micro_batch_size * SEQ / exp.measured_step_s
            row["measured_step_ms"] = round(exp.measured_step_s * 1e3, 1)
            row["measured_tflops"] = round(fpt * tok / 1e12, 2)
        rows.append(row)
    print(json.dumps({
        "backend": __import__("jax").default_backend(),
        "model": MODEL, "seq": SEQ, "elapsed_s": round(time.time() - t0, 1),
        "winner": best.name if best else None,
        "winner_measured_step_ms": (round(best.measured_step_s * 1e3, 1)
                                    if best and best.measured_step_s else None),
        "candidates": rows,
    }), flush=True)


if __name__ == "__main__":
    main()
